"""CLI tests: main() against a live API server over loopback.

Reference: the Go CLI's verb surface (cli/commands/*.go) — here the
CLI process boundary is exercised too (python -m dcos_commons_tpu.cli
in a subprocess for one smoke case; the rest call main() in-process).
"""

import json
import subprocess
import sys

import pytest

from dcos_commons_tpu.cli.commands import main
from dcos_commons_tpu.http import ApiServer
from dcos_commons_tpu.testing import (
    AdvanceCycles,
    ExpectDeploymentComplete,
    SendTaskRunning,
    ServiceTestRunner,
)

YAML = """
name: cli-svc
pods:
  app:
    count: 1
    tasks:
      main:
        goal: RUNNING
        cmd: "serve"
        cpus: 0.1
        memory: 32
"""


@pytest.fixture()
def deployed():
    runner = ServiceTestRunner(YAML)
    runner.run([
        AdvanceCycles(1),
        SendTaskRunning("app-0-main"),
        ExpectDeploymentComplete(),
    ])
    server = ApiServer(runner.world.scheduler).start()
    yield runner, server
    server.stop()


def cli(server, *argv, expect_rc=0, capsys=None):
    rc = main(["--url", server.url, *argv])
    assert rc == expect_rc
    out = capsys.readouterr().out if capsys else ""
    try:
        return json.loads(out)
    except json.JSONDecodeError:
        return out.strip()


def test_plan_and_pod_sections(deployed, capsys):
    runner, server = deployed
    assert cli(server, "plan", "list", capsys=capsys) == \
        ["autoscale", "deploy", "recovery"]
    plan = cli(server, "plan", "show", "deploy", capsys=capsys)
    assert plan["status"] == "COMPLETE"
    assert cli(server, "pod", "list", capsys=capsys) == ["app-0"]
    status = cli(server, "pod", "status", "app-0", capsys=capsys)
    assert status["tasks"][0]["status"] == "TASK_RUNNING"

    cli(server, "pod", "restart", "app-0", capsys=capsys)
    runner.run([AdvanceCycles(2), SendTaskRunning("app-0-main")])
    assert len(runner.agent.launches_of("app-0-main")) == 2

    # manual scale rides the autoscale plan machinery (ISSUE 15)
    scaled = cli(server, "pod", "scale", "app", "2", capsys=capsys)
    assert scaled["phase"] == "scale-out-app-2"
    runner.run([AdvanceCycles(2), SendTaskRunning("app-1-main")])
    assert cli(server, "pod", "list", capsys=capsys) == ["app-0", "app-1"]


def test_config_state_endpoints_health(deployed, capsys):
    runner, server = deployed
    target = cli(server, "config", "target", capsys=capsys)
    assert target["name"] == "cli-svc"
    target_id = cli(server, "config", "target_id", capsys=capsys)
    assert target_id in cli(server, "config", "list", capsys=capsys)
    props = cli(server, "state", "properties", capsys=capsys)
    assert "deployment-completed" in props
    health = cli(server, "health", capsys=capsys)
    assert health["healthy"]
    metrics = cli(server, "metrics", capsys=capsys)
    assert metrics["operations.launch"] >= 1
    offers = cli(server, "debug", "offers", capsys=capsys)
    assert offers["outcomes"][-1]["passed"]
    assert "snapshot_cache" in offers["evaluation"]


def test_plan_verbs(deployed, capsys):
    runner, server = deployed
    cli(server, "plan", "force-restart", "deploy", "app", "app-0:[main]",
        capsys=capsys)
    plan = cli(server, "plan", "show", "deploy", capsys=capsys)
    assert plan["status"] == "PENDING"
    cli(server, "plan", "force-complete", "deploy", "app", "app-0:[main]",
        capsys=capsys)
    plan = cli(server, "plan", "show", "deploy", capsys=capsys)
    assert plan["status"] == "COMPLETE"


def test_error_surfaces_as_exit_code(deployed, capsys):
    runner, server = deployed
    cli(server, "plan", "show", "nope", expect_rc=1, capsys=capsys)
    err = capsys.readouterr  # stderr captured alongside; rc checked above


def test_subprocess_smoke(deployed):
    runner, server = deployed
    result = subprocess.run(
        [sys.executable, "-m", "dcos_commons_tpu.cli",
         "--url", server.url, "plan", "list"],
        capture_output=True, text=True, timeout=30, cwd="/root/repo",
    )
    assert result.returncode == 0, result.stderr
    assert json.loads(result.stdout) == ["autoscale", "deploy", "recovery"]


def test_plan_start_stop_sidecar(deployed, capsys):
    """plan start/stop drive an interrupted sidecar plan end to end
    over the CLI (reference: cassandra backup via plan start)."""
    runner, server = deployed
    # rebuild the world with a sidecar plan service
    sidecar_yaml = """
name: cli-svc2
pods:
  app:
    count: 1
    tasks:
      main: {goal: RUNNING, cmd: "serve", cpus: 0.1, memory: 32}
      once: {goal: ONCE, cmd: "job", cpus: 0.1, memory: 32}
plans:
  deploy:
    strategy: serial
    phases:
      main-phase:
        strategy: serial
        pod: app
        steps:
          - 0: [[main]]
  backup:
    strategy: serial
    phases:
      backup-phase:
        strategy: serial
        pod: app
        steps:
          - 0: [[once]]
"""
    from dcos_commons_tpu.http import ApiServer
    from dcos_commons_tpu.testing import (
        SendTaskFinished,
        ServiceTestRunner,
    )

    side = ServiceTestRunner(sidecar_yaml)
    side.run([
        AdvanceCycles(1),
        SendTaskRunning("app-0-main"),
        ExpectDeploymentComplete(),
    ])
    server2 = ApiServer(side.world.scheduler).start()
    try:
        plans = cli(server2, "plan", "list", capsys=capsys)
        assert "backup" in plans
        cli(server2, "plan", "start", "backup", capsys=capsys)
        side.run([AdvanceCycles(1), SendTaskFinished("app-0-once")])
        status = cli(server2, "plan", "status", "backup", capsys=capsys)
        assert status["status"] == "COMPLETE"
        cli(server2, "plan", "stop", "backup", capsys=capsys)
        status = cli(server2, "plan", "status", "backup", capsys=capsys)
        assert status["status"] in ("WAITING", "PENDING")
    finally:
        server2.stop()


def test_pod_pause_resume_verbs(deployed, capsys):
    runner, server = deployed
    cli(server, "pod", "pause", "app-0", capsys=capsys)
    runner.run([AdvanceCycles(2)])
    status = cli(server, "pod", "status", "app-0", capsys=capsys)
    assert "PAUSING" in json.dumps(status)
    runner.run([AdvanceCycles(1), SendTaskRunning("app-0-main")])
    status = cli(server, "pod", "status", "app-0", capsys=capsys)
    assert "PAUSED" in json.dumps(status)
    cli(server, "pod", "resume", "app-0", capsys=capsys)
    runner.run([AdvanceCycles(2), SendTaskRunning("app-0-main")])
    status = cli(server, "pod", "status", "app-0", capsys=capsys)
    assert "PAUS" not in json.dumps(status)


def test_debug_and_metrics_sections(deployed, capsys):
    runner, server = deployed
    offers = cli(server, "debug", "offers", capsys=capsys)
    assert isinstance(offers, (list, dict))
    metrics = cli(server, "metrics", capsys=capsys)
    assert "offers.evaluated" in json.dumps(metrics)
    reservations = cli(server, "debug", "reservations", capsys=capsys)
    assert reservations


def test_debug_health_and_events_trackers(deployed, capsys):
    runner, server = deployed
    health = cli(server, "debug", "health", capsys=capsys)
    assert health["enabled"] is True
    assert health["status"] in ("ok", "warn")
    assert "suspect_hosts" in health and "journal" in health
    # --metric narrows to one series (sampled by the health pass the
    # deploy cycles already ran)
    one = cli(server, "debug", "health", "--metric", "cycle.process.count",
              capsys=capsys)
    assert one["history"]["metric"] == "cycle.process.count"
    assert isinstance(one["history"]["samples"], list)
    events = cli(server, "debug", "events", capsys=capsys)
    assert events["seq"] >= 1
    kinds = {e["kind"] for e in events["events"]}
    assert "plan" in kinds  # deploy step transitions were journaled
    # cursor resume: everything after the last seq is empty
    tail = cli(server, "debug", "events", "--since", str(events["seq"]),
               capsys=capsys)
    assert tail["events"] == []
    # kind filter
    plans = cli(server, "debug", "events", "--kind", "plan", capsys=capsys)
    assert plans["events"] and all(
        e["kind"] == "plan" for e in plans["events"]
    )
