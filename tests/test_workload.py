"""Workload library tests on the virtual 8-device CPU mesh.

Kernel correctness against jnp oracles (pallas interpret mode), ring
attention against dense attention, and the full sharded train step
compiling + running over a dp/fsdp/tp/sp mesh — the multi-chip path
the driver's dryrun exercises.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P
from dcos_commons_tpu.parallel.compat import shard_map

from dcos_commons_tpu.models import (
    MlpConfig,
    TransformerConfig,
    init_params,
    loss_fn,
    make_train_step,
    forward,
    mlp_init,
    mlp_train_step,
)
from dcos_commons_tpu.ops.attention import flash_attention
from dcos_commons_tpu.ops.rmsnorm import rms_norm
from dcos_commons_tpu.parallel.mesh import MeshSpec, make_mesh
from dcos_commons_tpu.parallel.ring import reference_attention, ring_attention
from dcos_commons_tpu.utils import (
    param_count,
    restore_checkpoint,
    save_checkpoint,
    synthetic_mnist,
    synthetic_tokens,
)


def test_eight_devices_available():
    assert len(jax.devices()) == 8, "conftest must force 8 CPU devices"


# -- kernels ----------------------------------------------------------


def test_flash_attention_matches_reference():
    key = jax.random.key(0)
    q, k, v = (
        jax.random.normal(k_, (2, 4, 256, 64), jnp.float32)
        for k_ in jax.random.split(key, 3)
    )
    oracle = reference_attention(q, k, v, causal=True)
    kernel = flash_attention(q, k, v, causal=True, interpret=True)
    np.testing.assert_allclose(
        np.asarray(kernel), np.asarray(oracle), atol=2e-5, rtol=2e-5
    )
    # non-causal too
    oracle_nc = reference_attention(q, k, v, causal=False)
    kernel_nc = flash_attention(q, k, v, causal=False, interpret=True)
    np.testing.assert_allclose(
        np.asarray(kernel_nc), np.asarray(oracle_nc), atol=2e-5, rtol=2e-5
    )


def test_flash_attention_backward_matches_reference_vjp():
    """The FA2 two-kernel backward (dq + dk/dv over the saved
    logsumexp) must match the dense reference VJP."""
    key = jax.random.key(2)
    q, k, v = (
        jax.random.normal(k_, (2, 3, 256, 64), jnp.float32)
        for k_ in jax.random.split(key, 3)
    )
    for causal in (True, False):
        def loss_kernel(q, k, v):
            return (
                flash_attention(
                    q, k, v, causal=causal, interpret=True,
                    force_pallas=True,
                ) ** 2
            ).sum()

        def loss_ref(q, k, v):
            return (reference_attention(q, k, v, causal=causal) ** 2).sum()

        grads_kernel = jax.grad(loss_kernel, argnums=(0, 1, 2))(q, k, v)
        grads_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for name, got, want in zip("qkv", grads_kernel, grads_ref):
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), atol=5e-5, rtol=5e-5,
                err_msg=f"d{name} causal={causal}",
            )


def test_flash_attention_ragged_falls_back():
    key = jax.random.key(1)
    q, k, v = (
        jax.random.normal(k_, (1, 2, 100, 32), jnp.float32)
        for k_ in jax.random.split(key, 3)
    )
    out = flash_attention(q, k, v, causal=True)  # 100 % 128 != 0
    oracle = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(oracle),
                               atol=2e-5, rtol=2e-5)


def test_rms_norm_matches_reference():
    x = jax.random.normal(jax.random.key(2), (512, 128), jnp.float32)
    w = jax.random.normal(jax.random.key(3), (128,), jnp.float32)
    kernel = rms_norm(x, w, interpret=True, block_rows=256)
    x32 = x.astype(jnp.float32)
    oracle = x32 * jax.lax.rsqrt(
        jnp.mean(x32 * x32, -1, keepdims=True) + 1e-6
    ) * w
    np.testing.assert_allclose(np.asarray(kernel), np.asarray(oracle),
                               atol=1e-5, rtol=1e-5)


# -- ring attention ---------------------------------------------------


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_dense(causal):
    mesh = make_mesh(MeshSpec(sp=8))
    key = jax.random.key(4)
    # global sequence 256 = 8 chunks of 32
    q, k, v = (
        jax.random.normal(k_, (2, 4, 256, 32), jnp.float32)
        for k_ in jax.random.split(key, 3)
    )
    oracle = reference_attention(q, k, v, causal=causal)

    ring = shard_map(
        functools.partial(ring_attention, axis_name="sp", causal=causal,
                          axis_size=8),
        mesh=mesh,
        in_specs=(P(None, None, "sp", None),) * 3,
        out_specs=P(None, None, "sp", None),
    )
    out = ring(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(oracle),
                               atol=2e-5, rtol=2e-5)


# -- transformer ------------------------------------------------------


SMALL = TransformerConfig(
    vocab=128, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
    d_ff=128, max_seq=64, dtype=jnp.float32, remat=False,
)


def test_transformer_forward_shapes():
    params = init_params(SMALL, jax.random.key(0))
    tokens, targets = synthetic_tokens(jax.random.key(1), 2, 32, SMALL.vocab)
    logits = forward(SMALL, params, tokens)
    assert logits.shape == (2, 32, SMALL.vocab)
    assert logits.dtype == jnp.float32
    assert param_count(params) > 0


def test_transformer_causality():
    """Changing a future token must not change past logits."""
    params = init_params(SMALL, jax.random.key(0))
    tokens, _ = synthetic_tokens(jax.random.key(1), 1, 32, SMALL.vocab)
    logits1 = forward(SMALL, params, tokens)
    perturbed = tokens.at[0, -1].set((tokens[0, -1] + 1) % SMALL.vocab)
    logits2 = forward(SMALL, params, perturbed)
    np.testing.assert_allclose(
        np.asarray(logits1[0, :-1]), np.asarray(logits2[0, :-1]),
        atol=1e-5, rtol=1e-5,
    )
    assert not np.allclose(np.asarray(logits1[0, -1]), np.asarray(logits2[0, -1]))


def test_transformer_training_reduces_loss():
    params = init_params(SMALL, jax.random.key(0))
    optimizer = optax.adam(1e-2)
    opt_state = optimizer.init(params)
    step = make_train_step(SMALL, optimizer)
    tokens, targets = synthetic_tokens(jax.random.key(1), 4, 32, SMALL.vocab)
    losses = []
    for _ in range(10):
        params, opt_state, loss = step(params, opt_state, tokens, targets)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.8, losses


def test_transformer_sharded_train_step():
    """The multi-chip path: dp=2 x fsdp=2 x tp=2 mesh, full train step."""
    mesh = make_mesh(MeshSpec(dp=2, fsdp=2, tp=2))
    config = TransformerConfig(
        vocab=128, d_model=64, n_layers=2, n_heads=4, n_kv_heads=4,
        d_ff=128, max_seq=32, dtype=jnp.float32, remat=True,
    )
    optimizer = optax.adam(1e-3)
    with mesh:
        params = init_params(config, jax.random.key(0))
        opt_state = optimizer.init(params)
        step = make_train_step(config, optimizer, mesh=mesh, donate=False)
        tokens, targets = synthetic_tokens(jax.random.key(1), 8, 32, config.vocab)
        params2, opt_state2, loss = step(params, opt_state, tokens, targets)
        assert jnp.isfinite(loss)
        # sharded result must equal the single-device result
        step_local = make_train_step(config, optimizer, donate=False)
        _, _, loss_local = step_local(params, opt_state, tokens, targets)
        np.testing.assert_allclose(float(loss), float(loss_local),
                                   atol=1e-4, rtol=1e-4)


def test_transformer_ring_attention_end_to_end():
    """sp=4: forward with ring attention == unsharded forward."""
    mesh = make_mesh(MeshSpec(sp=4, tp=2))
    config = TransformerConfig(
        vocab=128, d_model=64, n_layers=2, n_heads=4, n_kv_heads=4,
        d_ff=128, max_seq=64, dtype=jnp.float32, remat=False,
    )
    ring_config = TransformerConfig(
        **{**config.__dict__, "use_ring_attention": True}
    )
    params = init_params(config, jax.random.key(0))
    tokens, targets = synthetic_tokens(jax.random.key(1), 2, 64, config.vocab)
    oracle = loss_fn(config, params, tokens, targets)

    def body(params, tokens, targets):
        # per-chunk mean -> global mean (equal-sized chunks)
        local = loss_fn(ring_config, params, tokens, targets)
        return jax.lax.pmean(local, "sp")

    with mesh:
        ring_loss = shard_map(
            body,
            mesh=mesh,
            in_specs=(P(), P(None, "sp"), P(None, "sp")),
            out_specs=P(),
            check_vma=False,
        )
        loss = jax.jit(ring_loss)(params, tokens, targets)
    np.testing.assert_allclose(float(loss), float(oracle), atol=1e-4, rtol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_dense(causal):
    """All-to-all sequence parallelism: sp=4 Ulysses == dense oracle
    (the second long-context recipe next to the ring)."""
    from dcos_commons_tpu.parallel.ulysses import ulysses_attention

    mesh = make_mesh(MeshSpec(sp=4))
    key = jax.random.key(7)
    # 8 heads over sp=4 -> 2 heads/device; global sequence 256
    q, k, v = (
        jax.random.normal(k_, (2, 8, 256, 32), jnp.float32)
        for k_ in jax.random.split(key, 3)
    )
    oracle = reference_attention(q, k, v, causal=causal)
    uly = shard_map(
        functools.partial(
            ulysses_attention, axis_name="sp", causal=causal,
            block_q=64, block_k=64, axis_size=4,
        ),
        mesh=mesh,
        in_specs=(P(None, None, "sp", None),) * 3,
        out_specs=P(None, None, "sp", None),
        check_vma=False,
    )
    out = uly(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(oracle),
                               atol=2e-5, rtol=2e-5)


def test_ulysses_rejects_indivisible_heads():
    from dcos_commons_tpu.parallel.ulysses import ulysses_attention

    mesh = make_mesh(MeshSpec(sp=4))
    q = jnp.zeros((1, 6, 64, 8), jnp.float32)  # 6 heads % 4 != 0
    with pytest.raises(Exception, match="divisible"):
        shard_map(
            functools.partial(ulysses_attention, axis_name="sp",
                              axis_size=4),
            mesh=mesh,
            in_specs=(P(None, None, "sp", None),) * 3,
            out_specs=P(None, None, "sp", None),
            check_vma=False,
        )(q, q, q)


def test_transformer_ulysses_attention_end_to_end():
    """sp=4: forward with Ulysses attention == unsharded forward, and
    ring == ulysses on the same params (both recipes interchangeable
    behind TransformerConfig.sp_axis)."""
    mesh = make_mesh(MeshSpec(sp=4, tp=2))
    config = TransformerConfig(
        vocab=128, d_model=64, n_layers=2, n_heads=4, n_kv_heads=4,
        d_ff=128, max_seq=64, dtype=jnp.float32, remat=False,
    )
    uly_config = TransformerConfig(
        **{**config.__dict__, "use_ulysses_attention": True}
    )
    params = init_params(config, jax.random.key(0))
    tokens, targets = synthetic_tokens(jax.random.key(1), 2, 64, config.vocab)
    oracle = loss_fn(config, params, tokens, targets)

    def body(params, tokens, targets):
        local = loss_fn(uly_config, params, tokens, targets)
        return jax.lax.pmean(local, "sp")

    with mesh:
        uly_loss = shard_map(
            body,
            mesh=mesh,
            in_specs=(P(), P(None, "sp"), P(None, "sp")),
            out_specs=P(),
            check_vma=False,
        )
        loss = jax.jit(uly_loss)(params, tokens, targets)
    np.testing.assert_allclose(float(loss), float(oracle), atol=1e-4, rtol=1e-4)


def test_config_rejects_both_sp_recipes():
    with pytest.raises(ValueError, match="ONE sequence-parallel"):
        TransformerConfig(use_ring_attention=True,
                          use_ulysses_attention=True)


# -- MoE flagship variant --------------------------------------------


MOE_CFG = TransformerConfig(
    vocab=128, d_model=64, n_layers=2, n_heads=4, n_kv_heads=4,
    d_ff=128, max_seq=64, dtype=jnp.float32, remat=False,
    n_experts=4, moe_top_k=2,
)


def test_moe_transformer_trains():
    """n_experts > 0: every layer's FFN is a routed expert mixture;
    the train step moves the loss and grads reach router + experts."""
    import optax

    params = init_params(MOE_CFG, jax.random.key(0))
    assert params["layers"]["router"].shape == (2, 64, 4)
    assert params["layers"]["w_gate"].shape == (2, 4, 64, 128)
    tokens, targets = synthetic_tokens(jax.random.key(1), 4, 64, 128)
    optimizer = optax.adam(1e-2)
    step = make_train_step(MOE_CFG, optimizer, donate=False)
    opt_state = optimizer.init(params)
    p, o, loss0 = step(params, opt_state, tokens, targets)
    for _ in range(20):
        p, o, loss = step(p, o, tokens, targets)
    assert jnp.isfinite(loss) and float(loss) < float(loss0)
    router_delta = jnp.abs(
        p["layers"]["router"] - params["layers"]["router"]
    ).max()
    assert float(router_delta) > 0  # the router actually learns


def test_moe_transformer_sharded_train_step():
    """The MoE flagship under a dp x ep mesh: expert params shard over
    ep and the jitted (GSPMD) step runs — the jit-native counterpart
    of the dryrun's explicit shard_map all_to_all path."""
    import optax

    mesh = make_mesh(MeshSpec(dp=2, ep=4))
    optimizer = optax.adam(1e-3)
    with mesh:
        params = init_params(MOE_CFG, jax.random.key(0))
        opt_state = optimizer.init(params)
        step = make_train_step(MOE_CFG, optimizer, mesh=mesh, donate=False)
        tokens, targets = synthetic_tokens(jax.random.key(1), 4, 64, 128)
        p, o, loss = step(params, opt_state, tokens, targets)
        loss.block_until_ready()
    assert bool(jnp.isfinite(loss))
    # expert weights really live sharded over ep
    sharding = p["layers"]["w_gate"].sharding
    assert "ep" in (sharding.spec[1] or ())


def test_moe_generate_matches_forward_chain():
    """KV-cache decode works for the MoE variant too: decode routes
    DROP-FREE, so greedy generate equals argmax-chained full forwards
    whenever the forward side is also in its drop-free regime (the
    capacity factor here guarantees that; with training-style capacity
    pressure, dropped tokens make forwards differ from ANY drop-free
    server by construction).  Checked across several seeds — routing
    equivalence must not be seed luck."""
    from dcos_commons_tpu.models import generate

    cfg = TransformerConfig(
        **{**MOE_CFG.__dict__, "moe_capacity_factor": 8.0}
    )
    for seed in range(5):
        params = init_params(cfg, jax.random.key(seed))
        prompt, _ = synthetic_tokens(
            jax.random.key(100 + seed), 2, 6, cfg.vocab
        )
        out = generate(cfg, params, prompt, max_new_tokens=4)
        seq = prompt
        for i in range(4):
            nxt = jnp.argmax(
                forward(cfg, params, seq)[:, -1], axis=-1
            ).astype(jnp.int32)
            np.testing.assert_array_equal(
                np.asarray(out[:, i]), np.asarray(nxt),
                err_msg=f"moe decode divergence seed {seed} step {i}",
            )
            seq = jnp.concatenate([seq, nxt[:, None]], axis=1)


def test_moe_generate_with_int8_kv_cache():
    """The int8 KV cache composes with the MoE variant (the quantized
    path is FFN-agnostic): generation runs and closely tracks the
    exact cache."""
    from dcos_commons_tpu.models import generate

    cfg = TransformerConfig(
        **{**MOE_CFG.__dict__, "moe_capacity_factor": 8.0}
    )
    params = init_params(cfg, jax.random.key(0))
    prompt, _ = synthetic_tokens(jax.random.key(7), 2, 6, cfg.vocab)
    exact = generate(cfg, params, prompt, max_new_tokens=8)
    quant = generate(
        cfg, params, prompt, max_new_tokens=8, kv_dtype="int8"
    )
    assert quant.shape == exact.shape
    agree = float(jnp.mean((exact == quant).astype(jnp.float32)))
    assert agree >= 0.75, f"only {agree:.0%} of greedy tokens agree"


def test_moe_rejected_in_pipeline_path():
    from dcos_commons_tpu.models import pipeline_forward

    params = init_params(MOE_CFG, jax.random.key(0))
    tokens, _ = synthetic_tokens(jax.random.key(3), 2, 64, 128)
    with pytest.raises(NotImplementedError, match="not pipelined"):
        pipeline_forward(MOE_CFG, params, tokens, n_micro=2)


# -- mlp + checkpointing ---------------------------------------------


def test_mlp_trains():
    config = MlpConfig(dtype=jnp.float32)
    params = mlp_init(config, jax.random.key(0))
    optimizer = optax.adam(1e-3)
    opt_state = optimizer.init(params)
    step = mlp_train_step(optimizer)
    x, y = synthetic_mnist(jax.random.key(1), 64)
    losses = []
    for _ in range(20):
        params, opt_state, loss = step(params, opt_state, x, y)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_checkpoint_roundtrip(tmp_path):
    config = MlpConfig(dtype=jnp.float32)
    params = mlp_init(config, jax.random.key(0))
    save_checkpoint(str(tmp_path), 7, params)
    like = mlp_init(config, jax.random.key(1))
    restored, step = restore_checkpoint(str(tmp_path), like)
    assert step == 7
    np.testing.assert_array_equal(
        np.asarray(restored["w1"]), np.asarray(params["w1"])
    )
    # empty dir: returns like, None
    _, none_step = restore_checkpoint(str(tmp_path / "empty"), like)
    assert none_step is None


def test_checkpoint_retention(tmp_path):
    """keep=K prunes to the newest K AFTER the new save is durable;
    keep=0 keeps everything; the latest step always restores."""
    tree = {"w": jnp.ones((2, 2), jnp.float32)}
    # a stray operator file in the directory must neither crash the
    # pruner nor be pruned (review r5)
    (tmp_path / "step_best.npz").write_bytes(b"not a checkpoint")
    for step in (1, 2, 3, 4):
        save_checkpoint(str(tmp_path), step, tree, keep=2)
    names = sorted(p.name for p in tmp_path.glob("step_*.npz"))
    assert names == [
        "step_0000000003.npz", "step_0000000004.npz", "step_best.npz",
    ]
    restored, step = restore_checkpoint(str(tmp_path), tree)
    assert step == 4
    # keep=0 (default): nothing pruned
    save_checkpoint(str(tmp_path), 5, tree)
    assert len(list(tmp_path.glob("step_0*.npz"))) == 3
    # an explicitly requested absent step errors, never silent-fresh
    import pytest

    with pytest.raises(FileNotFoundError):
        restore_checkpoint(str(tmp_path), tree, step=99)
    # a hand-named (unpadded) checkpoint restores and prunes by its
    # LISTED name
    import shutil

    shutil.copy(
        tmp_path / "step_0000000005.npz", tmp_path / "step_7.npz"
    )
    restored, step = restore_checkpoint(str(tmp_path), tree)
    assert step == 7
    save_checkpoint(str(tmp_path), 8, tree, keep=1)
    names = sorted(p.name for p in tmp_path.glob("step_*.npz"))
    assert names == ["step_0000000008.npz", "step_best.npz"]
    # ROLLBACK + retrain: saving a step OLDER than existing files
    # keeps the checkpoint just written AND prunes the abandoned
    # future (review r5 x2) — the default latest-step resume must
    # find the retrain, not the state the rollback undid
    save_checkpoint(str(tmp_path), 2, tree, keep=1)
    names = sorted(p.name for p in tmp_path.glob("step_0*.npz"))
    assert names == ["step_0000000002.npz"]
    restored, step = restore_checkpoint(str(tmp_path), tree)
    assert step == 2
    # the operator's non-step snapshot survives every prune
    assert (tmp_path / "step_best.npz").exists()


def test_checkpoint_bf16_roundtrip(tmp_path):
    """bf16 leaves must survive the npz round-trip (review regression)."""
    tree = {"w": jnp.ones((4, 4), jnp.bfloat16) * 1.5,
            "count": jnp.zeros((), jnp.int32)}
    save_checkpoint(str(tmp_path), 3, tree)
    like = {"w": jnp.zeros((4, 4), jnp.bfloat16),
            "count": jnp.zeros((), jnp.int32)}
    restored, step = restore_checkpoint(str(tmp_path), like)
    assert step == 3
    assert restored["w"].dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(restored["w"].astype(jnp.float32)),
        np.full((4, 4), 1.5, np.float32),
    )
