"""HTTP API tests: real sockets against a sim-harness scheduler.

Reference: the /v1 surface of http/queries/PlansQueries.java,
PodQueries.java, endpoints/*.java, exercised here over loopback.
"""

import json
import urllib.request
import urllib.error

import pytest

from dcos_commons_tpu.http import ApiServer
from dcos_commons_tpu.testing import (
    AdvanceCycles,
    ExpectDeploymentComplete,
    SendTaskRunning,
    ServiceTestRunner,
)

YAML = """
name: api-svc
pods:
  web:
    count: 2
    tasks:
      srv:
        goal: RUNNING
        cmd: "serve"
        cpus: 0.1
        memory: 32
        ports:
          http:
            env-key: PORT_HTTP
"""


@pytest.fixture()
def deployed():
    runner = ServiceTestRunner(YAML)
    runner.run([
        AdvanceCycles(1),
        SendTaskRunning("web-0-srv"),
        AdvanceCycles(1),
        SendTaskRunning("web-1-srv"),
        ExpectDeploymentComplete(),
    ])
    server = ApiServer(runner.world.scheduler).start()
    yield runner, server
    server.stop()


def get(server, path, expect_code=200):
    try:
        with urllib.request.urlopen(server.url + path) as resp:
            code, raw = resp.status, resp.read()
    except urllib.error.HTTPError as e:
        code, raw = e.code, e.read()
    assert code == expect_code, f"GET {path} -> {code}: {raw[:200]}"
    content = raw.decode("utf-8")
    try:
        return json.loads(content)
    except json.JSONDecodeError:
        return content  # text/plain bodies (ids, properties, prometheus)


def post(server, path, expect_code=200):
    req = urllib.request.Request(server.url + path, method="POST", data=b"")
    try:
        with urllib.request.urlopen(req) as resp:
            code, raw = resp.status, resp.read()
    except urllib.error.HTTPError as e:
        code, raw = e.code, e.read()
    assert code == expect_code, f"POST {path} -> {code}: {raw[:200]}"
    return json.loads(raw.decode("utf-8"))


def test_health_and_plans(deployed):
    runner, server = deployed
    health = get(server, "/v1/health")
    assert health["healthy"] and health["deployed"]

    # `autoscale` is the health-action engine's (empty-until-used)
    # dynamic plan, present on every scheduler since ISSUE 15
    assert get(server, "/v1/plans") == ["autoscale", "deploy", "recovery"]
    plan = get(server, "/v1/plans/deploy")
    assert plan["status"] == "COMPLETE"
    assert plan["phases"][0]["steps"][0]["status"] == "COMPLETE"
    get(server, "/v1/plans/nope", expect_code=404)


def test_pod_surface(deployed):
    runner, server = deployed
    assert get(server, "/v1/pod") == ["web-0", "web-1"]
    statuses = get(server, "/v1/pod/status")
    assert statuses["service"] == "api-svc"
    instance = get(server, "/v1/pod/web-0/status")
    assert instance["tasks"][0]["status"] == "TASK_RUNNING"
    info = get(server, "/v1/pod/web-0/info")
    assert info[0]["name"] == "web-0-srv"

    # restart kills the task; the scheduler relaunches it via recovery
    result = post(server, "/v1/pod/web-0/restart")
    assert result["tasks"] == ["web-0-srv"]
    runner.run([
        AdvanceCycles(2),
        SendTaskRunning("web-0-srv"),
    ])
    assert len(runner.agent.launches_of("web-0-srv")) == 2

    post(server, "/v1/pod/bogus-x/restart", expect_code=400)
    post(server, "/v1/pod/nope-0/restart", expect_code=404)


def test_pause_resume_verbs(deployed):
    runner, server = deployed
    # resuming a pod that was never paused is a rejected no-op: nothing
    # may be killed (reference: PodQueries transition validation)
    post(server, "/v1/pod/web-1/resume", expect_code=409)
    assert runner.agent.kills == []
    result = post(server, "/v1/pod/web-1/pause")
    assert result["tasks"] == ["web-1-srv"]
    post(server, "/v1/pod/web-1/pause", expect_code=409)
    runner.run([AdvanceCycles(2), SendTaskRunning("web-1-srv")])
    from dcos_commons_tpu.offer.evaluate import PAUSE_COMMAND

    assert runner.agent.task_info_of("web-1-srv").command == PAUSE_COMMAND
    post(server, "/v1/pod/web-1/resume")
    runner.run([AdvanceCycles(2), SendTaskRunning("web-1-srv")])
    assert runner.agent.task_info_of("web-1-srv").command == "serve"


def test_configs_state_endpoints_debug_metrics(deployed):
    runner, server = deployed
    target_id = get(server, "/v1/configs/targetId")
    assert target_id in get(server, "/v1/configs")
    target = get(server, "/v1/configs/target")
    assert target["name"] == "api-svc"

    props = get(server, "/v1/state/properties")
    assert "deployment-completed" in props
    assert get(server, "/v1/state/properties/deployment-completed") is True
    zones = get(server, "/v1/state/zones")
    assert set(zones) == {"host-0", "host-1", "host-2"}

    endpoints = get(server, "/v1/endpoints")
    assert "http" in endpoints
    ep = get(server, "/v1/endpoints/http")
    assert len(ep["address"]) == 2

    offers = get(server, "/v1/debug/offers")
    assert offers["outcomes"] and offers["outcomes"][-1]["passed"]
    evaluation = offers["evaluation"]
    assert evaluation["snapshot_cache"]["hits"] >= 0
    assert "last_dirty_hosts" in evaluation
    assert evaluation["counters"].get("offers.evaluated", 0) >= 1
    reservations = get(server, "/v1/debug/reservations")
    assert len(reservations) >= 2
    metrics = get(server, "/v1/metrics")
    assert metrics["operations.launch"] >= 2
    prom = get(server, "/v1/metrics/prometheus")
    assert "operations_launch" in prom


def test_debug_trace_routes(deployed):
    """traceview surface: text timeline + Chrome (Perfetto) JSON."""
    runner, server = deployed
    text = get(server, "/v1/debug/trace")
    assert isinstance(text, str) and text.startswith("# trace:")
    assert "cycle" in text and "status:TASK_RUNNING" in text

    chrome = get(server, "/v1/debug/trace?fmt=chrome")
    events = chrome["traceEvents"]
    assert events and all(e["ph"] == "X" for e in events)
    assert all(e["pid"] == "api-svc" for e in events)
    tids = {e["tid"] for e in events}
    assert "web-0" in tids and "web-1" in tids  # pod lanes
    names = {e["name"] for e in events}
    assert any(n.startswith("launch:web") for n in names)
    assert any(n.startswith("evaluate:web") for n in names)

    get(server, "/v1/debug/trace?fmt=bogus", expect_code=400)


def test_debug_trace_empty_recorder(deployed):
    runner, server = deployed
    from dcos_commons_tpu.trace import TraceRecorder

    runner.world.scheduler.tracer = TraceRecorder(capacity=16)
    chrome = get(server, "/v1/debug/trace?fmt=chrome")
    assert chrome["traceEvents"] == []
    assert chrome["otherData"]["dropped"] == 0
    text = get(server, "/v1/debug/trace")
    assert "(0 dropped" in text
    # an empty RECORDER still renders the journal lane (the deploy's
    # plan transitions journaled): every non-header row is journal
    rows = [l for l in text.splitlines() if not l.startswith("#")]
    assert rows and all(" journal " in row for row in rows)


def test_debug_trace_truncation_reports_dropped(deployed):
    runner, server = deployed
    from dcos_commons_tpu.trace import TraceRecorder

    scheduler = runner.world.scheduler
    scheduler.tracer = TraceRecorder(capacity=4, metrics=scheduler.metrics)
    for i in range(10):
        scheduler.tracer.event(f"overflow-{i}", track="scheduler")
    chrome = get(server, "/v1/debug/trace?fmt=chrome")
    assert len(chrome["traceEvents"]) == 4  # ring keeps the newest
    assert chrome["otherData"]["dropped"] == 6
    assert "(6 dropped" in get(server, "/v1/debug/trace")
    # evictions are observable as a metric, too
    assert get(server, "/v1/metrics")["trace.dropped"] == 6


def test_debug_serving_route(deployed):
    """Serving-load surface: {} when no worker wrote gauges; merged
    per-task snapshots when the agent surfaces servestats files
    (serve/engine.py mirrors its gauges to the sandbox)."""
    runner, server = deployed
    # the sim harness agent has no sandboxes: empty, not an error
    assert get(server, "/v1/debug/serving") == {"serving": {}}

    stats = {
        "slots": 8, "queue_depth": 3, "active_slots": 5,
        "kv_occupancy": 0.42, "tokens_per_s": 123.4,
    }

    class _ServingAgent:
        def serving_stats_of(self, task_name):
            return dict(stats) if task_name == "web-0-srv" else {}

    scheduler = runner.world.scheduler
    original = scheduler.agent
    scheduler.agent = _ServingAgent()
    try:
        body = get(server, "/v1/debug/serving")
        assert body["serving"] == {"web-0-srv": stats}
    finally:
        scheduler.agent = original


ADVERTISE_YAML = """
name: adv-svc
pods:
  server:
    count: 2
    tasks:
      api:
        goal: RUNNING
        cmd: "serve"
        cpus: 0.1
        memory: 32
        ports:
          http:
            env-key: PORT_HTTP
            vip: "inference:80"
            advertise: true
"""


def test_endpoint_advertised_ports_generation_and_backends():
    """The routing-tier discovery contract (ISSUE 12): `advertise:
    true` ports list the worker's actually-bound port (servestats
    annotation via the agent), the body carries backend rows with
    drain state, and the generation stamp moves only when the task/
    reservation surface does."""
    runner = ServiceTestRunner(ADVERTISE_YAML)
    runner.run([
        AdvanceCycles(1),
        SendTaskRunning("server-0-api"),
        AdvanceCycles(1),
        SendTaskRunning("server-1-api"),
        ExpectDeploymentComplete(),
    ])
    scheduler = runner.world.scheduler
    server = ApiServer(scheduler).start()

    class _AdvertisingAgent:
        def advertised_port_of(self, task_name, agent_id=None):
            return 4242 if task_name == "server-0-api" else None

    original = scheduler.agent
    scheduler.agent = _AdvertisingAgent()
    try:
        ep = get(server, "/v1/endpoints/vip:inference")
        assert ep["generation"]
        # server-0 advertises its real bind; server-1 keeps the
        # reserved port (no annotation -> reservation fallback)
        by_task = {row["task"]: row for row in ep["backends"]}
        assert by_task["server-0-api"]["address"].endswith(":4242")
        assert not by_task["server-1-api"]["address"].endswith(":4242")
        assert by_task["server-0-api"]["draining"] is False
        assert set(ep["address"]) == {
            by_task["server-0-api"]["address"],
            by_task["server-1-api"]["address"],
        }
        # quiet fleet: the stamp is stable across reads...
        gen = ep["generation"]
        assert get(server, "/v1/endpoints/vip:inference")["generation"] \
            == gen
        # ...and moves on a task mutation (pause -> draining backend)
        post(server, "/v1/pod/server-1/pause")
        ep2 = get(server, "/v1/endpoints/vip:inference")
        assert ep2["generation"] != gen
        by_task2 = {row["task"]: row for row in ep2["backends"]}
        assert by_task2["server-1-api"]["draining"] is True
    finally:
        scheduler.agent = original
        server.stop()


def test_debug_router_route(deployed):
    """Front-door state surface: router tasks split out of the
    serving merge by the router_pods marker; the endpoint generation
    rides along for discovery triage."""
    runner, server = deployed
    body = get(server, "/v1/debug/router")
    assert body["routers"] == {}
    assert body["endpoints_generation"]

    router_stats = {
        "router_pods": 3, "router_affinity_hit_rate": 0.8,
        "queue_depth": 2, "stats_age_s": 0.0,
    }
    serve_stats = {"queue_depth": 1, "active_slots": 2}

    class _MixedAgent:
        def serving_stats_of(self, task_name):
            if task_name == "web-0-srv":
                return dict(router_stats)
            return dict(serve_stats)

    scheduler = runner.world.scheduler
    original = scheduler.agent
    scheduler.agent = _MixedAgent()
    try:
        body = get(server, "/v1/debug/router")
        # only the router task appears; plain serve gauges stay out
        assert body["routers"] == {"web-0-srv": router_stats}
    finally:
        scheduler.agent = original


def test_plan_verbs_over_http(deployed):
    runner, server = deployed
    # a COMPLETE plan stays COMPLETE through interrupt/continue
    post(server, "/v1/plans/deploy/interrupt")
    assert get(server, "/v1/plans/deploy")["status"] == "COMPLETE"
    post(server, "/v1/plans/deploy/continue")
    assert get(server, "/v1/plans/deploy")["status"] == "COMPLETE"
    # restart a single step by name, then force it complete again
    post(server, "/v1/plans/deploy/restart?phase=web&step=web-1:%5Bsrv%5D")
    assert get(server, "/v1/plans/deploy", expect_code=202)["status"] == \
        "IN_PROGRESS"
    post(server, "/v1/plans/deploy/forceComplete?phase=web&step=web-1:%5Bsrv%5D")
    assert get(server, "/v1/plans/deploy")["status"] == "COMPLETE"
