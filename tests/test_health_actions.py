"""The closed health->action loop (ISSUE 15): decision-rule
properties, the quiet-pod low-watermark detector, and the acceptance
scenario end to end — a seeded serving SLO breach under load
synthesizes a scale-out plan that deploys through the normal offer
cycle, the SLO recovers, a later sustained quiet period synthesizes a
scale-in that flips the victim's /v1/endpoints rows to draining and
waits out the router grace BEFORE any kill fires, everything is
journaled and operator-interruptible, and a failover neither re-fires
a completed action nor forgets an in-flight one (latches seeded from
the replayed journal).  Chaos kills the scheduler at every scale-plan
boundary and asserts convergence with zero duplicate actions.
"""

import random

import pytest

from dcos_commons_tpu.common import TaskState, TaskStatus
from dcos_commons_tpu.health.actions import (
    ActionPolicy,
    Decision,
    decide,
    remediation_allowed,
    scale_out_target,
    seed_latches,
)
from dcos_commons_tpu.health.detectors import (
    QuietPodWatcher,
    ServingSloWatcher,
)
from dcos_commons_tpu.http.api import SchedulerApi
from dcos_commons_tpu.scheduler.config import SchedulerConfig
from dcos_commons_tpu.testing import (
    AdvanceCycles,
    ExpectDeploymentComplete,
    SendTaskRunning,
    ServiceTestRunner,
)

SERVE_YAML = """
name: svc
pods:
  serve:
    count: 1
    tasks:
      server:
        goal: RUNNING
        cmd: "python serve.py"
        cpus: 0.5
        memory: 256
        ports:
          web:
            env-key: PORT_WEB
"""

# pod-level decommission needs the YAML opt-in (validation rejects a
# count shrink otherwise); the autoscale loop manages counts through
# the live-spec verb, which the opt-in does not gate
DECOMMISSION_YAML = SERVE_YAML.replace(
    "count: 1", "count: 2\n    allow-decommission: true"
)


def autoscale_config(**overrides) -> SchedulerConfig:
    base = dict(
        backoff_enabled=False,
        revive_capacity=10**9,
        health_autoscale=True,
        health_queue_depth_slo=10.0,
        autoscale_max_instances=3,
        autoscale_breach_hold_s=0.0,
        autoscale_quiet_hold_s=0.0,
        # large: within one test, each direction fires at most once
        # (run_cycle's own observe passes use the wall clock, so a
        # zero cooldown would let wall-time passes re-fire actions
        # between the test's explicit synthetic-now passes)
        autoscale_cooldown_out_s=1e6,
        autoscale_cooldown_in_s=1e6,
        autoscale_drain_grace_s=30.0,
    )
    base.update(overrides)
    return SchedulerConfig(**base)


def inject_stats(monitor, stats):
    """Feed the detectors directly (the telemetry fan-in itself is
    test_health's subject; these tests own the ACTION seam): park
    collection far in the future so _observe scores the injected
    snapshot instead of re-collecting over the FakeAgent."""
    monitor.telemetry_interval_s = 1e9
    monitor._last_telemetry = 1e18
    monitor._serving_stats = dict(stats)
    monitor._serving_env = {t: {} for t in stats}
    monitor._telemetry_seq += 1


def deploy_serve(config=None, count_running=1):
    runner = ServiceTestRunner(
        SERVE_YAML, scheduler_config=config or autoscale_config()
    )
    runner.run([
        AdvanceCycles(1),
        *[SendTaskRunning(f"serve-{i}-server")
          for i in range(count_running)],
        ExpectDeploymentComplete(),
    ])
    return runner


def ack_new_running(world):
    """RUNNING+ready for every launch not yet acked."""
    acked = world.extras.setdefault("acked", set())
    for info in list(world.agent.launched):
        if info.task_id in acked:
            continue
        acked.add(info.task_id)
        world.agent.send(TaskStatus(
            task_id=info.task_id, state=TaskState.RUNNING,
            ready=True, agent_id=info.agent_id,
        ))


def drive(world, cycles=8):
    for _ in range(cycles):
        world.scheduler.run_cycle()
        ack_new_running(world)


POLICY = ActionPolicy(
    autoscale=True, max_instances=4, breach_hold_s=10.0,
    quiet_hold_s=60.0, cooldown_out_s=30.0, cooldown_in_s=120.0,
)


# -- the pure decision rule -------------------------------------------


def test_scale_out_target_monotone_and_clamped():
    for count in range(1, 5):
        prev = count
        for severity in [0.5, 1.0, 1.5, 2.0, 3.9, 4.0, 9.0, 100.0]:
            target = scale_out_target(count, 6, severity, step_max=3)
            assert target >= prev  # monotone in severity
            assert count <= target <= 6
            prev = target
    # the step cap and the instance cap both bind
    assert scale_out_target(1, 8, 1e9, step_max=2) == 3
    assert scale_out_target(7, 8, 1e9, step_max=4) == 8


def test_decide_breach_path():
    assert decide(
        100.0, policy=POLICY, count=2, baseline=1,
        breach_since=80.0, severity=2.0,
    ) == Decision("out", 4)
    # hysteresis hold not yet satisfied
    assert decide(
        85.0, policy=POLICY, count=2, baseline=1,
        breach_since=80.0, severity=2.0,
    ) is None
    # cooldown suppresses
    assert decide(
        100.0, policy=POLICY, count=2, baseline=1,
        breach_since=80.0, severity=2.0, cooldown_out_until=150.0,
    ) is None
    # at the ceiling: no-op decision is NO decision
    assert decide(
        100.0, policy=POLICY, count=4, baseline=1,
        breach_since=0.0, severity=9.0,
    ) is None


def test_decide_quiet_path_and_floor():
    assert decide(
        1000.0, policy=POLICY, count=3, baseline=1, quiet_since=900.0,
    ) == Decision("in", 2)
    # never below the YAML floor
    assert decide(
        1000.0, policy=POLICY, count=1, baseline=1, quiet_since=0.0,
    ) is None
    # cooldown and hold
    assert decide(
        1000.0, policy=POLICY, count=3, baseline=1, quiet_since=990.0,
    ) is None
    assert decide(
        1000.0, policy=POLICY, count=3, baseline=1, quiet_since=0.0,
        cooldown_in_until=2000.0,
    ) is None


def test_decide_single_flight_hold_and_precedence():
    # an in-flight action of EITHER direction suppresses everything
    for active in ("out", "in"):
        assert decide(
            1e6, policy=POLICY, count=2, baseline=1,
            breach_since=0.0, severity=9.0, quiet_since=0.0,
            active=active,
        ) is None
    # flap hold (open lease-churn episode) suppresses everything
    assert decide(
        1e6, policy=POLICY, count=2, baseline=1, breach_since=0.0,
        severity=9.0, hold=True,
    ) is None
    # breach dominates quiet: one state can never emit "in"
    decision = decide(
        1e6, policy=POLICY, count=3, baseline=1,
        breach_since=0.0, severity=2.0, quiet_since=0.0,
    )
    assert decision is not None and decision.direction == "out"
    # disabled policy decides nothing
    assert decide(
        1e6, policy=ActionPolicy(autoscale=False), count=2, baseline=1,
        breach_since=0.0, severity=9.0,
    ) is None


def test_constant_signal_never_oscillates():
    """The hysteresis band: replay a CONSTANT signal against the
    breach threshold and the quiet watermark and fold the emitted
    directions — at most ONE direction ever fires, whatever the
    value (in the dead band, neither)."""
    threshold, factor = 10.0, 0.25
    for value in [0.0, 1.0, 2.5, 2.6, 5.0, 9.9, 10.0, 10.1, 40.0]:
        breaching = value > threshold
        quiet = value <= threshold * factor
        assert not (breaching and quiet)
        directions = set()
        count, cooldowns = 2, {"out": 0.0, "in": 0.0}
        for now in range(0, 2000, 50):
            decision = decide(
                float(now), policy=POLICY, count=count, baseline=1,
                breach_since=0.0 if breaching else None,
                severity=value / threshold if breaching else 1.0,
                quiet_since=0.0 if quiet else None,
                cooldown_out_until=cooldowns["out"],
                cooldown_in_until=cooldowns["in"],
            )
            if decision is None:
                continue
            directions.add(decision.direction)
            count = decision.target
            cooldowns[decision.direction] = now + (
                POLICY.cooldown_out_s if decision.direction == "out"
                else POLICY.cooldown_in_s
            )
        assert len(directions) <= 1, (value, directions)


def _scale_events():
    return [
        {"seq": 1, "verb": "scale-out", "stage": "start", "pod": "a",
         "from": 1, "to": 3, "t": 10.0},
        {"seq": 2, "verb": "scale-out", "stage": "complete", "pod": "a",
         "from": 1, "to": 3, "t": 20.0},
        {"seq": 3, "verb": "auto-replace", "host": "h1", "t": 25.0},
        {"seq": 4, "verb": "scale-in", "stage": "start", "pod": "a",
         "from": 3, "to": 2, "t": 400.0},
        {"seq": 5, "verb": "scale-in", "stage": "complete", "pod": "a",
         "from": 3, "to": 2, "t": 410.0},
        {"seq": 6, "verb": "scale-out", "stage": "start", "pod": "b",
         "from": 2, "to": 4, "t": 500.0},
    ]


def test_seed_latches_fold_and_permutation_invariance():
    events = _scale_events()
    in_flight, done_t, last_replace = seed_latches(events)
    assert in_flight == {
        "b": {"direction": "out", "from": 2, "to": 4, "t": 500.0}
    }
    assert done_t == {("a", "out"): 20.0, ("a", "in"): 410.0}
    assert last_replace == 25.0
    # cooldown invariance under episode-event permutation: the fold
    # orders by journal seq, so shuffles cannot change the outcome
    for seed in range(12):
        shuffled = list(events)
        random.Random(seed).shuffle(shuffled)
        assert seed_latches(shuffled) == (in_flight, done_t,
                                          last_replace)


def test_remediation_allowed_gates():
    assert remediation_allowed(
        100.0, enabled=True, scale_active=False, hold=False,
        last_replace_t=None, cooldown_s=300.0,
    )
    assert not remediation_allowed(
        100.0, enabled=False, scale_active=False, hold=False,
        last_replace_t=None, cooldown_s=300.0,
    )
    # never while a scale plan for the service is active
    assert not remediation_allowed(
        100.0, enabled=True, scale_active=True, hold=False,
        last_replace_t=None, cooldown_s=300.0,
    )
    assert not remediation_allowed(
        100.0, enabled=True, scale_active=False, hold=True,
        last_replace_t=None, cooldown_s=300.0,
    )
    assert not remediation_allowed(
        100.0, enabled=True, scale_active=False, hold=False,
        last_replace_t=50.0, cooldown_s=300.0,
    )
    assert remediation_allowed(
        1000.0, enabled=True, scale_active=False, hold=False,
        last_replace_t=50.0, cooldown_s=300.0,
    )


# -- hypothesis properties (skipped without the package) --------------


try:  # pragma: no cover - availability varies by container
    from hypothesis import given, settings
    from hypothesis import strategies as st

    HAVE_HYPOTHESIS = True
except ImportError:  # pragma: no cover
    HAVE_HYPOTHESIS = False


if HAVE_HYPOTHESIS:

    @settings(max_examples=200, deadline=None)
    @given(
        count=st.integers(1, 8),
        cap=st.integers(1, 10),
        severities=st.lists(
            st.floats(0.1, 1e6, allow_nan=False), min_size=2,
            max_size=6,
        ),
    )
    def test_hyp_scale_out_target_monotone(count, cap, severities):
        targets = [
            scale_out_target(count, cap, s, step_max=3)
            for s in sorted(severities)
        ]
        assert targets == sorted(targets)
        assert all(count <= t <= max(cap, count) for t in targets)

    @settings(max_examples=150, deadline=None)
    @given(
        value=st.floats(0.0, 100.0, allow_nan=False),
        baseline=st.integers(1, 3),
        start_count=st.integers(1, 6),
    )
    def test_hyp_constant_signal_single_direction(
        value, baseline, start_count
    ):
        threshold, factor = 10.0, 0.25
        breaching = value > threshold
        quiet = value <= threshold * factor
        directions = set()
        count = max(start_count, baseline)
        cooldowns = {"out": 0.0, "in": 0.0}
        for now in range(0, 3000, 37):
            decision = decide(
                float(now), policy=POLICY, count=count,
                baseline=baseline,
                breach_since=0.0 if breaching else None,
                severity=max(1.0, value / threshold),
                quiet_since=0.0 if quiet else None,
                cooldown_out_until=cooldowns["out"],
                cooldown_in_until=cooldowns["in"],
            )
            if decision is None:
                continue
            directions.add(decision.direction)
            count = decision.target
            cooldowns[decision.direction] = now + 30.0
        assert len(directions) <= 1

    @settings(max_examples=100, deadline=None)
    @given(seed=st.integers(0, 10_000))
    def test_hyp_seed_latches_permutation_invariant(seed):
        events = _scale_events()
        shuffled = list(events)
        random.Random(seed).shuffle(shuffled)
        assert seed_latches(shuffled) == seed_latches(events)


# -- the quiet-pod watcher --------------------------------------------


def test_quiet_watcher_episodes_and_dead_band():
    slo = ServingSloWatcher(queue_depth_slo=10.0, ttft_p95_slo_s=1.0)
    quiet = QuietPodWatcher(slo, quiet_factor=0.25)
    busy = {"t": {"queue_depth": 40.0, "ttft_p95_s": 0.1}}
    idle = {"t": {"queue_depth": 0.0, "ttft_p95_s": 0.01}}
    band = {"t": {"queue_depth": 5.0, "ttft_p95_s": 0.1}}

    assert quiet.observe(busy, now=1.0) == []
    events = quiet.observe(idle, now=2.0)
    assert [e["detector"] for e in events] == ["quiet"]
    assert quiet.quiet_since == {"t": 2.0}
    # still quiet: edge-triggered, no repeat; since is preserved
    assert quiet.observe(idle, now=3.0) == []
    assert quiet.quiet_since == {"t": 2.0}
    # the dead band (above the watermark, below the SLO): clears
    # quiet but test_constant_signal... shows it breaches nothing
    cleared = quiet.observe(band, now=4.0)
    assert cleared and cleared[0].get("cleared")
    assert quiet.quiet_since == {}
    assert slo.observe(band, now=4.0) == []  # not a breach either


def test_quiet_watcher_missed_samples_and_min_direction():
    slo = ServingSloWatcher(queue_depth_slo=10.0,
                            kv_pages_free_slo=16.0)
    quiet = QuietPodWatcher(slo, quiet_factor=0.25)
    idle = {"t": {"queue_depth": 0.0, "kv_pages_free": 100.0}}
    quiet.observe(idle, now=1.0)
    assert "t" in quiet.quiet_since
    # a missing sample is not a recovery; three in a row retires
    assert quiet.observe({}, now=2.0) == []
    assert quiet.observe({}, now=3.0) == []
    assert "t" in quiet.quiet_since
    assert quiet.observe({}, now=4.0) == []
    assert "t" not in quiet.quiet_since
    # a breaching MIN-direction signal (kv pages exhausted) is the
    # opposite of quiet even with an empty queue
    starved = {"t": {"queue_depth": 0.0, "kv_pages_free": 2.0}}
    assert quiet.observe(starved, now=5.0) == []
    assert "t" not in quiet.quiet_since


def test_slo_watcher_records_breach_since_and_severity():
    slo = ServingSloWatcher(queue_depth_slo=10.0)
    slo.observe({"t": {"queue_depth": 40.0}}, now=100.0)
    assert slo.breach_since[("t", "queue_depth")] == 100.0
    assert slo.breach_severity[("t", "queue_depth")] == 4.0
    # still breaching: since keeps the episode start, severity tracks
    slo.observe({"t": {"queue_depth": 80.0}}, now=110.0)
    assert slo.breach_since[("t", "queue_depth")] == 100.0
    assert slo.breach_severity[("t", "queue_depth")] == 8.0
    slo.observe({"t": {"queue_depth": 1.0}}, now=120.0)
    assert slo.breach_since == {} and slo.breach_severity == {}


# -- the closed loop, end to end --------------------------------------


def test_closed_loop_breach_scale_out_recover_quiet_scale_in():
    """The acceptance scenario: breach -> scale-out deploys through
    the normal offer cycle -> SLO recovers -> sustained quiet ->
    scale-in with the endpoints draining flip and router grace
    BEFORE the kill -> journal carries the audited episode pairs."""
    runner = deploy_serve()
    world = runner.world
    scheduler = world.scheduler
    monitor = scheduler.health
    api = SchedulerApi(scheduler)
    clock = [0.0]
    scheduler.actions._clock = lambda: clock[0]

    # seeded SLO breach under load (queue depth 4x its SLO)
    inject_stats(monitor, {"serve-0-server": {"queue_depth": 40.0}})
    events = monitor._observe(scheduler, 1000.0)
    starts = [e for e in events if e.get("stage") == "start"]
    assert [e["verb"] for e in starts] == ["scale-out"]
    assert starts[0]["to"] == 3 and starts[0]["from"] == 1
    # trace correlation back to the triggering episode
    assert starts[0]["task"] == "serve-0-server"
    assert starts[0]["signal"] == "queue_depth"
    phase = scheduler.actions.manager.phase_for("serve")
    assert phase.name == "scale-out-serve-3"

    drive(world, cycles=8)
    assert scheduler.spec.pod("serve").count == 3
    names = {i.name for i in world.agent.launched}
    assert {"serve-1-server", "serve-2-server"} <= names
    assert phase.is_complete
    # settled (run_cycle's own observe passes): completion journaled,
    # cooldown clock started, phase pruned
    assert scheduler.actions.manager.phase_for("serve") is None
    assert ("serve", "out") in scheduler.actions._done_t
    assert any(
        e.get("stage") == "complete"
        for e in scheduler.journal.events(kinds=("health",))
    )

    # recovered SLO, then a sustained quiet period on ALL instances
    idle = {
        f"serve-{i}-server": {"queue_depth": 0.5} for i in range(3)
    }
    inject_stats(monitor, idle)
    events = monitor._observe(scheduler, 2000.0)
    assert any(
        e.get("detector") == "slo" and e.get("cleared") for e in events
    )
    starts = [e for e in events if e.get("stage") == "start"]
    assert [e["verb"] for e in starts] == ["scale-in"]
    phase = scheduler.actions.manager.phase_for("serve")
    assert phase.name == "scale-in-serve-2"
    assert scheduler.draining_instances() == {"serve-2"}

    # drive the shrink + drain start; the kill must NOT fire inside
    # the router drain grace, while the endpoints surface shows the
    # victim draining with its task still RUNNING on a healthy host
    clock[0] = 3000.0
    world.scheduler.run_cycle()
    world.scheduler.run_cycle()
    assert scheduler.spec.pod("serve").count == 2
    victim_id = world.agent.task_id_of("serve-2-server")
    assert victim_id not in world.agent.kills
    _code, endpoint = api.get_endpoint("web")
    rows = {r["task"]: r for r in endpoint["backends"]}
    assert rows["serve-2-server"]["draining"] is True
    assert rows["serve-2-server"]["state"] == "TASK_RUNNING"
    assert rows["serve-0-server"]["draining"] is False

    # grace elapses -> kill -> unreserve -> erase
    clock[0] = 3031.0
    drive(world, cycles=6)
    assert victim_id in world.agent.kills
    assert scheduler.state_store.fetch_task("serve-2-server") is None
    assert scheduler.ledger.for_task("serve-2-server") == []
    assert scheduler.actions.manager.phase_for("serve") is None

    # the audited, flap-free episode record: start/complete pairs in
    # strict alternation, no opposite-direction overlap
    stages = [
        (e["verb"], e["stage"])
        for e in scheduler.journal.events(kinds=("health",))
        if e.get("stage")
    ]
    assert stages == [
        ("scale-out", "start"), ("scale-out", "complete"),
        ("scale-in", "start"), ("scale-in", "complete"),
    ]


def test_scale_plan_is_operator_interruptible():
    """An automated action is a plan like any other: interrupt parks
    it (single flight holds, nothing else fires), proceed resumes."""
    runner = deploy_serve()
    world = runner.world
    scheduler = world.scheduler
    monitor = scheduler.health
    api = SchedulerApi(scheduler)

    inject_stats(monitor, {"serve-0-server": {"queue_depth": 40.0}})
    monitor._observe(scheduler, 1000.0)
    code, _body = api.plan_interrupt("autoscale")
    assert code == 200
    drive(world, cycles=4)
    phase = scheduler.actions.manager.phase_for("serve")
    assert phase is not None and not phase.is_complete
    # interrupted-but-active: still single-flight, no second action
    inject_stats(monitor, {"serve-0-server": {"queue_depth": 90.0}})
    events = monitor._observe(scheduler, 1500.0)
    assert not [e for e in events if e.get("stage") == "start"]
    code, _body = api.plan_continue("autoscale")
    assert code == 200
    drive(world, cycles=8)
    assert phase.is_complete
    assert scheduler.spec.pod("serve").count == 3


def test_failover_resumes_in_flight_action_without_refire():
    """Action latches and cooldown clocks are seeded from the
    replayed journal: a successor RESUMES the in-flight scale-out
    (idempotent steps, deployment steps re-seeded from state) and a
    later successor sees the completed action's cooldown instead of
    re-firing it."""
    runner = deploy_serve()
    world = runner.world
    scheduler = world.scheduler
    monitor = scheduler.health

    inject_stats(monitor, {"serve-0-server": {"queue_depth": 40.0}})
    monitor._observe(scheduler, 1000.0)
    # grow + first launches land; the action is mid-flight
    world.scheduler.run_cycle()
    assert scheduler.spec.pod("serve").count == 3
    launched_before = {i.name for i in world.agent.launched}

    # the scheduler dies; a successor rebuilds over the same store
    runner2 = runner.restart()
    world2 = runner2.build()
    scheduler2 = world2.scheduler
    world2.scheduler.run_cycle()  # rehydrate: seed + restore plans
    phase = scheduler2.actions.manager.phase_for("serve")
    assert phase is not None and phase.name == "scale-out-serve-3"
    assert scheduler2.spec.pod("serve").count == 3
    drive(world2, cycles=8)
    assert phase.is_complete
    # no duplicate action, no duplicate deploys: one start event,
    # one complete event, and the successor re-launched nothing that
    # already ran
    completes = [
        e for e in scheduler2.journal.events(kinds=("health",))
        if e.get("stage") == "complete"
    ]
    assert len(completes) == 1
    starts = [
        e for e in scheduler2.journal.events(kinds=("health",))
        if e.get("stage") == "start"
    ]
    assert len(starts) == 1
    relaunched = [
        i.name for i in world2.agent.launched
        if i.name in launched_before
    ]
    assert len(relaunched) == len(launched_before)

    # a THIRD incarnation seeds the completed action as a cooldown
    # latch, not an in-flight plan
    runner3 = runner2.restart()
    world3 = runner3.build()
    world3.scheduler.run_cycle()
    engine3 = world3.scheduler.actions
    assert engine3.manager.phase_for("serve") is None
    assert ("serve", "out") in engine3._done_t


CHAOS_BOUNDARIES = (
    "post-evaluate",
    "post-wal",
    "mid-status-fan-in",
    "mid-plan-transition",
)


@pytest.mark.parametrize("kind", CHAOS_BOUNDARIES)
def test_chaos_kill_at_scale_plan_boundary(kind):
    """Kill the scheduler at every span boundary of a scale-out
    plan's deploy work: the successor converges, the journal carries
    exactly ONE scale action, and no reservation is double-held."""
    from dcos_commons_tpu.testing.chaos import (
        CrashInjector,
        KillPoint,
        SchedulerKilled,
    )

    runner = deploy_serve()
    world = runner.world
    scheduler = world.scheduler
    inject_stats(scheduler.health,
                 {"serve-0-server": {"queue_depth": 40.0}})
    scheduler.health._observe(scheduler, 1000.0)
    scheduler.chaos = CrashInjector(KillPoint(kind, 1))

    killed = False
    for _ in range(24):
        try:
            world.scheduler.run_cycle()
        except SchedulerKilled:
            killed = True
            runner = runner.restart()
            world = runner.build()
            scheduler = world.scheduler
            inject_stats(scheduler.health,
                         {"serve-0-server": {"queue_depth": 40.0}})
            continue
        ack_new_running(world)
        phase = scheduler.actions.manager.phase_for("serve")
        if phase is None or phase.is_complete:
            if scheduler.spec.pod("serve").count == 3 and all(
                scheduler.state_store.fetch_task(f"serve-{i}-server")
                is not None
                for i in range(3)
            ):
                break
    assert killed, f"kill point {kind} never fired"
    assert scheduler.spec.pod("serve").count == 3
    # exactly one audited action across both incarnations
    starts = [
        e for e in scheduler.journal.events(kinds=("health",))
        if e.get("stage") == "start"
    ]
    assert len(starts) == 1, starts
    # zero double-reservations: every claim belongs to a stored task,
    # at most one claim set per task name
    stored = {i.name for i in scheduler.state_store.fetch_tasks()}
    seen = {}
    for reservation in scheduler.ledger.all():
        assert reservation.task_name in stored
        key = (reservation.task_name, reservation.host_id)
        assert seen.setdefault(key, reservation.reservation_id) == \
            reservation.reservation_id


# -- single flight across plan families + the multi discipline --------


def test_remediation_suppressed_while_scale_plan_active():
    runner = deploy_serve(config=autoscale_config(
        health_remediation=True,
    ))
    world = runner.world
    scheduler = world.scheduler
    monitor = scheduler.health

    inject_stats(monitor, {"serve-0-server": {"queue_depth": 40.0}})
    monitor._observe(scheduler, 1000.0)
    assert scheduler.actions.manager.phase_for("serve") is not None
    # a straggler episode lands while the scale plan is in flight:
    # remediation must NOT fire (no storm)
    straggler = [{
        "kind": "alert", "detector": "straggler",
        "host": world.agent.launched[0].agent_id, "score": 5.0,
    }]
    out = scheduler.actions.remediate(
        scheduler, straggler, True, now=1001.0
    )
    assert out == []
    # once the scale action settles, the same episode may remediate
    drive(world, cycles=8)
    assert scheduler.actions.manager.phase_for("serve") is None
    out = scheduler.actions.remediate(
        scheduler, straggler, True, now=1011.0
    )
    assert len(out) == 1 and out[0]["verb"] == "auto-replace"


def test_recovery_defers_to_in_flight_scale_action():
    """A failed scale-out launch is the SCALE phase's to retry:
    recovery treats an instance owned by an incomplete autoscale step
    as externally managed, exactly as it defers to an incomplete
    deploy step — otherwise the two plans would trade launches for
    the same task names."""
    runner = deploy_serve()
    world = runner.world
    scheduler = world.scheduler
    inject_stats(scheduler.health,
                 {"serve-0-server": {"queue_depth": 40.0}})
    scheduler.health._observe(scheduler, 1000.0)
    world.scheduler.run_cycle()  # grow
    world.scheduler.run_cycle()  # launch serve-1
    failed = world.agent.task_id_of("serve-1-server")
    assert failed is not None
    world.agent.send(TaskStatus(
        task_id=failed, state=TaskState.FAILED,
        message="boom", agent_id="host-0",
    ))
    world.scheduler.run_cycle()  # route the failure
    recovery = scheduler.plan("recovery")
    assert not any(
        "serve-1" in s.get_asset_names()
        for p in recovery.phases for s in p.steps
    ), [p.name for p in recovery.phases]
    # the scale phase itself retries the launch and completes
    drive(world, cycles=8)
    assert scheduler.actions.manager.phase_for("serve") is None or \
        scheduler.actions.manager.phase_for("serve").is_complete
    assert scheduler.spec.pod("serve").count == 3
    status = scheduler.state_store.fetch_status("serve-1-server")
    assert status is not None and status.state is TaskState.RUNNING


def test_scale_out_counts_as_growth_for_offer_discipline():
    """Bounded concurrent growth across services: a service with an
    active scale-out plan reads as 'growing', so the multi
    scheduler's ParallelFootprintDiscipline bounds how many services
    scale out at once (the OfferDiscipline enforcement point)."""
    from dcos_commons_tpu.multi.scheduler import MultiServiceScheduler

    runner = deploy_serve()
    world = runner.world
    scheduler = world.scheduler
    assert not MultiServiceScheduler._is_growing(scheduler)
    inject_stats(scheduler.health,
                 {"serve-0-server": {"queue_depth": 40.0}})
    scheduler.health._observe(scheduler, 1000.0)
    assert MultiServiceScheduler._is_growing(scheduler)
    drive(world, cycles=8)
    scheduler.health._observe(scheduler, 1010.0)
    assert not MultiServiceScheduler._is_growing(scheduler)


# -- operator surfaces ------------------------------------------------


def test_pod_scale_verb_and_single_flight_conflict():
    runner = deploy_serve(config=autoscale_config(
        health_autoscale=False,  # manual scale works with the loop off
    ))
    world = runner.world
    scheduler = world.scheduler
    api = SchedulerApi(scheduler)

    code, body = api.pod_scale("serve", {"count": 2})
    assert code == 200 and body["phase"] == "scale-out-serve-2"
    # single flight: a second scale while one is in flight is a 409
    code, body = api.pod_scale("serve", {"count": 3})
    assert code == 409
    code, _body = api.pod_scale("nope", {"count": 2})
    assert code == 404
    code, _body = api.pod_scale("serve", {"count": "two"})
    assert code == 400
    drive(world, cycles=8)
    assert scheduler.spec.pod("serve").count == 2
    assert scheduler.actions.manager.phase_for("serve") is None
    # scale-in goes one instance at a time
    code, body = api.pod_scale("serve", {"count": 1})
    assert code == 200 and body["phase"] == "scale-in-serve-1"
    drive(world, cycles=8)
    assert scheduler.spec.pod("serve").count == 1
    # never below the YAML floor: the restart overlay would silently
    # undo it — the verb refuses and points at the YAML path
    code, body = api.pod_scale("serve", {"count": 0})
    assert code == 400


def test_surplus_decommission_flips_endpoint_draining():
    """The satellite proper: a POD-LEVEL decommission (count shrunk
    in the target spec — no autoscale involved) flips the surplus
    backend's endpoint rows to draining while its task is still
    RUNNING and its host healthy, BEFORE the kill completes."""
    import dataclasses

    runner = ServiceTestRunner(
        DECOMMISSION_YAML,
        scheduler_config=SchedulerConfig(
            backoff_enabled=False, revive_capacity=10**9,
        ),
    )
    runner.run([
        AdvanceCycles(1),
        SendTaskRunning("serve-0-server"),
        SendTaskRunning("serve-1-server"),
        ExpectDeploymentComplete(),
    ])
    # the operator shrinks the spec: a restart builds the surplus
    # decommission plan for serve-1
    shrunk = dataclasses.replace(
        runner.spec,
        pods=tuple(
            dataclasses.replace(p, count=1) for p in runner.spec.pods
        ),
    )
    runner2 = ServiceTestRunner(
        spec=shrunk, persister=runner.persister,
        scheduler_config=runner.config,
    )
    runner2.agent = runner.agent
    runner2.inventory = runner.inventory
    runner2.agent.auto_ack_kills = False  # hold the kill un-acked
    world2 = runner2.build()
    scheduler2 = world2.scheduler
    api = SchedulerApi(scheduler2)
    assert scheduler2.plan("decommission") is not None
    assert scheduler2.draining_instances() == {"serve-1"}
    world2.scheduler.run_cycle()  # kill issued, not yet acked
    # the count shrink is also a config update: serve-0 rolls to the
    # new target — ack its relaunch so the survivor row is healthy
    ack_new_running(world2)
    world2.scheduler.run_cycle()
    _code, endpoint = api.get_endpoint("web")
    rows = {r["task"]: r for r in endpoint["backends"]}
    assert rows["serve-1-server"]["draining"] is True
    assert rows["serve-1-server"]["state"] == "TASK_RUNNING"
    assert rows["serve-0-server"]["draining"] is False


def test_remediation_hold_covers_whole_churn_episode():
    """The lease-churn alert event fires only on the episode's
    OPENING edge; the hold must ride the stateful episode flag, or a
    straggler alert one pass later would replace a pod under
    flapping leadership."""
    runner = deploy_serve(config=autoscale_config(
        health_remediation=True, health_autoscale=False,
    ))
    scheduler = runner.world.scheduler
    straggler = [{
        "kind": "alert", "detector": "straggler",
        "host": runner.world.agent.launched[0].agent_id, "score": 5.0,
    }]
    # episode open (no edge event in THIS pass): still held
    out = scheduler.actions.remediate(
        scheduler, straggler, True, now=100.0, hold=True,
    )
    assert out == []
    out = scheduler.actions.remediate(
        scheduler, straggler, True, now=101.0, hold=False,
    )
    assert len(out) == 1


def test_quiet_needs_a_load_signal_not_just_headroom():
    """Min-direction headroom signals veto quiet but never attest:
    with only kv_pages_free_slo enabled, a loaded-but-not-starved
    pod must read UNKNOWN, not quiet (the scale-in it would trigger
    breaches and flaps)."""
    slo = ServingSloWatcher(kv_pages_free_slo=16.0)
    quiet = QuietPodWatcher(slo, quiet_factor=0.25)
    plenty = {"t": {"kv_pages_free": 100.0}}
    assert quiet.observe(plenty, now=1.0) == []
    assert quiet.quiet_since == {}


def test_task_owner_longest_type_match():
    """Pod 'web-2''s tasks must never attribute to pod 'web'."""
    import dataclasses

    from dcos_commons_tpu.health.actions import HealthActionEngine
    from dcos_commons_tpu.specification.yaml_spec import from_yaml

    spec = from_yaml(SERVE_YAML)
    twin = dataclasses.replace(spec.pods[0], type="serve-2")
    spec = dataclasses.replace(spec, pods=spec.pods + (twin,))
    owner = HealthActionEngine._task_owner
    assert owner(spec, "serve-0-server") == ("serve", 0)
    assert owner(spec, "serve-2-0-server") == ("serve-2", 0)
    assert owner(spec, "serve-2-3-server") == ("serve-2", 3)
    assert owner(spec, "unrelated-0-x") is None


def test_abandon_settles_count_to_deployed_reality():
    """Abandoning a half-deployed scale-out reverts the persisted
    count to the contiguous deployed prefix — otherwise the next
    restart's count overlay would silently resume the abandoned
    widening."""
    runner = deploy_serve()
    world = runner.world
    scheduler = world.scheduler
    inject_stats(scheduler.health,
                 {"serve-0-server": {"queue_depth": 40.0}})
    scheduler.health._observe(scheduler, 1000.0)  # start 1 -> 3
    world.scheduler.run_cycle()  # grow: count = 3
    world.scheduler.run_cycle()  # serve-1 launched (not yet acked)
    assert scheduler.spec.pod("serve").count == 3
    assert scheduler.actions.abandon(scheduler, "serve")
    # serve-1 has a stored task, serve-2 does not: settle at 2
    assert scheduler.spec.pod("serve").count == 2
    raw = scheduler.state_store.fetch_property("autoscale-count-serve")
    assert raw == b"2@1"  # count @ the YAML floor it was written against
    abandoned = [
        e for e in scheduler.journal.events(kinds=("health",))
        if e.get("stage") == "abandoned"
    ]
    assert abandoned and abandoned[0]["settled"] == 2
    # the abandonment is terminal: the out-direction cooldown latched
    assert ("serve", "out") in scheduler.actions._done_t


def test_failover_mid_scale_in_honors_drain_grace():
    """The successor of a scheduler killed mid-scale-in must NOT
    build a drain-less surplus-decommission phase for the victim:
    the journal-latched scale-in owns the teardown, and its drain
    step re-waits the FULL router grace before any kill."""
    runner = deploy_serve()
    world = runner.world
    scheduler = world.scheduler
    monitor = scheduler.health
    clock = [0.0]
    scheduler.actions._clock = lambda: clock[0]

    inject_stats(monitor, {"serve-0-server": {"queue_depth": 40.0}})
    monitor._observe(scheduler, 1000.0)
    drive(world, cycles=8)  # scale-out to 3 completes + settles
    idle = {
        f"serve-{i}-server": {"queue_depth": 0.5} for i in range(3)
    }
    inject_stats(monitor, idle)
    monitor._observe(scheduler, 2000.0)  # scale-in starts
    clock[0] = 3000.0
    world.scheduler.run_cycle()  # shrink (count persists at 2) + drain starts
    victim_id = world.agent.task_id_of("serve-2-server")

    # kill -9; the successor rebuilds over the persisted count
    runner2 = runner.restart()
    world2 = runner2.build()
    scheduler2 = world2.scheduler
    clock2 = [5000.0]
    scheduler2.actions._clock = lambda: clock2[0]
    # NO decommission phase for the victim: the scale-in owns it
    decommission = scheduler2.plan("decommission")
    assert decommission is None or not any(
        "serve-2" in getattr(p, "decommission_targets", set())
        for p in decommission.phases
    )
    inject_stats(scheduler2.health, idle)
    for _ in range(6):
        world2.scheduler.run_cycle()
    # inside the re-started grace: victim alive, rows draining
    assert victim_id not in world2.agent.kills
    assert scheduler2.draining_instances() == {"serve-2"}
    clock2[0] = 5031.0  # the FULL grace elapses on the successor
    drive(world2, cycles=8)
    assert victim_id in world2.agent.kills
    assert scheduler2.state_store.fetch_task("serve-2-server") is None


def test_pod_scale_abandon_verb():
    runner = deploy_serve(config=autoscale_config(
        health_autoscale=False,
    ))
    world = runner.world
    scheduler = world.scheduler
    api = SchedulerApi(scheduler)
    code, _body = api.pod_scale_abandon("serve")
    assert code == 409  # nothing in flight
    code, _body = api.pod_scale("serve", {"count": 3})
    assert code == 200
    world.scheduler.run_cycle()  # grow only; no deploys acked
    code, body = api.pod_scale_abandon("serve")
    assert code == 200 and body["abandoned"] is True
    # settled back to the deployed single instance
    assert scheduler.spec.pod("serve").count == 1
    assert scheduler.actions.manager.phase_for("serve") is None
    code, _body = api.pod_scale_abandon("nope")
    assert code == 404


def test_manual_scale_settles_without_health_plane():
    """HEALTH_ENABLED=false wires the NullHealthMonitor, which never
    calls the engine's settle pass — the scale verbs settle terminal
    phases themselves, so single flight can never wedge a
    health-disabled scheduler."""
    runner = deploy_serve(config=autoscale_config(
        health_enabled=False, health_autoscale=False,
    ))
    world = runner.world
    scheduler = world.scheduler
    api = SchedulerApi(scheduler)
    code, _body = api.pod_scale("serve", {"count": 2})
    assert code == 200
    drive(world, cycles=8)
    assert scheduler.actions.manager.phase_for("serve") is not None
    assert scheduler.actions.manager.phase_for("serve").is_complete
    # a second scale settles the completed phase instead of 409ing
    code, body = api.pod_scale("serve", {"count": 3})
    assert code == 200, body
    # and abandon of a COMPLETED phase settles it as complete too —
    # never a false 'abandoned' journal stage
    drive(world, cycles=8)
    assert scheduler.abandon_scale("serve") is False


def test_yaml_count_change_invalidates_stale_override():
    """The persisted count is stamped with the YAML floor it was
    written against: an operator's config update that CHANGES the
    declared count drops the stale autoscale decision — the overlay
    must never neutralize a YAML count decrease."""
    import dataclasses

    from dcos_commons_tpu.scheduler.builder import (
        _apply_autoscale_counts,
    )
    from dcos_commons_tpu.specification.yaml_spec import from_yaml
    from dcos_commons_tpu.state.state_store import StateStore
    from dcos_commons_tpu.storage import MemPersister

    spec = from_yaml(SERVE_YAML)  # serve: count 1
    store = StateStore(MemPersister())
    store.store_property("autoscale-count-serve", b"4@1")
    # unchanged YAML floor: the override applies
    overlaid, baselines = _apply_autoscale_counts(spec, store)
    assert overlaid.pod("serve").count == 4
    assert baselines == {"serve": 1}
    # the operator moves the YAML count: the stale override is dropped
    wider = dataclasses.replace(
        spec,
        pods=tuple(
            dataclasses.replace(p, count=2) for p in spec.pods
        ),
    )
    overlaid, baselines = _apply_autoscale_counts(wider, store)
    assert overlaid.pod("serve").count == 2
    assert baselines == {"serve": 2}
    # corrupt property: ignored
    store.store_property("autoscale-count-serve", b"junk")
    overlaid, _ = _apply_autoscale_counts(spec, store)
    assert overlaid.pod("serve").count == 1


def test_scale_out_steps_inherit_launch_backoff():
    """A crash-looping scaled-out instance backs off like a
    deploy-plan instance, not hot-retrying every cycle."""
    from dcos_commons_tpu.plan.backoff import ExponentialBackoff

    runner = deploy_serve(config=autoscale_config(
        backoff_enabled=True,
    ))
    scheduler = runner.world.scheduler
    assert isinstance(scheduler.actions.backoff, ExponentialBackoff)
    inject_stats(scheduler.health,
                 {"serve-0-server": {"queue_depth": 40.0}})
    scheduler.health._observe(scheduler, 1000.0)
    phase = scheduler.actions.manager.phase_for("serve")
    deploy_steps = [
        s for s in phase.steps if hasattr(s, "requirement")
    ]
    assert deploy_steps and all(
        isinstance(s._backoff, ExponentialBackoff) for s in deploy_steps
    )


def test_debug_health_exposes_action_state():
    runner = deploy_serve()
    scheduler = runner.world.scheduler
    inject_stats(scheduler.health,
                 {"serve-0-server": {"queue_depth": 40.0}})
    scheduler.health._observe(scheduler, 1000.0)
    api = SchedulerApi(scheduler)
    _code, body = api.debug_health()
    actions = body["actions"]
    assert actions["enabled"] is True
    assert actions["active"]["serve"]["direction"] == "out"
    assert actions["active"]["serve"]["to"] == 3
    assert any(
        e.get("verb") == "scale-out" for e in actions["recent"]
    )
    # quiet watcher state rides the detector block
    assert "quiet" in body["slo"] or "quiet" in body
