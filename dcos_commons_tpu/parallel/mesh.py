"""Device mesh construction + named sharding helpers.

Axes vocabulary (scaling-book conventions):
    dcn   cross-slice data parallel — batch split ACROSS ICI slices,
          gradient allreduce rides the data-center network (the only
          collective that should: params replicate over dcn)
    dp    data parallel — batch split, gradient allreduce
    fsdp  fully-sharded data parallel — params/optimizer sharded,
          all-gathered per layer
    ep    expert parallel — MoE experts split, all_to_all dispatch
    pp    pipeline parallel — layer stages split, ppermute activations
    tp    tensor parallel — heads/ffn split, activation collectives
    sp    sequence/context parallel — ring attention over sequence
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec


@dataclass(frozen=True)
class MeshSpec:
    """Logical mesh shape; axes with size 1 are kept (harmless)."""

    dp: int = 1
    fsdp: int = 1
    tp: int = 1
    sp: int = 1
    pp: int = 1
    ep: int = 1
    dcn: int = 1

    @property
    def total(self) -> int:
        return (self.dcn * self.dp * self.fsdp * self.tp * self.sp
                * self.pp * self.ep)

    def axes(self) -> Dict[str, int]:
        return {
            "dcn": self.dcn,
            "dp": self.dp,
            "fsdp": self.fsdp,
            "ep": self.ep,
            "pp": self.pp,
            "sp": self.sp,
            "tp": self.tp,
        }


def make_mesh(spec: MeshSpec, devices: Optional[Sequence] = None) -> Mesh:
    """Build a Mesh whose device order follows the hardware order.

    jax puts same-host devices adjacent in jax.devices(); keeping the
    fastest-varying mesh axis (tp) innermost maps tp collectives onto
    intra-host ICI first — the scaling-book layout rule.
    """
    devices = list(devices if devices is not None else jax.devices())
    if len(devices) < spec.total:
        raise ValueError(
            f"mesh {spec} needs {spec.total} devices, have {len(devices)}"
        )
    devices = devices[: spec.total]
    # tp innermost (intra-host ICI), then sp ring, then pp neighbors,
    # then ep all_to_alls; dp/fsdp outer, and dcn OUTERMOST — jax
    # orders devices slice-by-slice, so the leading axis is exactly
    # the slice boundary and only dcn collectives cross it
    arr = np.array(devices).reshape(
        spec.dcn, spec.dp, spec.fsdp, spec.ep, spec.pp, spec.sp, spec.tp
    )
    return Mesh(arr, ("dcn", "dp", "fsdp", "ep", "pp", "sp", "tp"))


def derive(env: Dict[str, str], n_devices: Optional[int] = None) -> MeshSpec:
    """Derive the MeshSpec from the scheduler's env contract — PURE
    shape math, no device queries, so analyzers (analysis/shardcheck)
    evaluate it abstractly and :func:`mesh_from_env` builds the real
    mesh from the same derivation.

    TPU_TOPOLOGY "XxY" at TPU_CHIPS_PER_HOST chips/host: default to
    dp over hosts x tp within host — the layout the torus placement
    guarantees is ICI-contiguous.  Multi-slice gangs (TPU_NUM_SLICES)
    lay a dcn axis over the slice boundary.

    Without ``n_devices`` the chip count comes from the declared
    topology (times slices), i.e. what the spec promises at deploy.
    A declared TPU_TOPOLOGY whose per-slice chip count
    TPU_CHIPS_PER_HOST does not divide raises SpecError: that spec can
    never lay the promised host-aligned mesh, and silently falling
    back to a pure-dp layout would train with a layout the operator
    never asked for.  With no topology declared (ad-hoc envs, local
    dryruns) the fallback stays graceful.
    """
    from dcos_commons_tpu.specification.specs import SpecError

    # 0 is the "probe the local runtime" sentinel, not a chip count;
    # options.json's 4 only applies to rendered deploys
    # sdklint: disable=config-default-drift — autodetect sentinel
    chips_per_host = int(env.get("TPU_CHIPS_PER_HOST", "0") or 0)
    n_slices = int(env.get("TPU_NUM_SLICES", "1") or 1)
    topology = env.get("TPU_TOPOLOGY", "")
    if n_devices is None:
        if topology:
            try:
                dims = [int(d) for d in topology.lower().split("x")]
            except ValueError:
                raise SpecError(f"bad topology {topology!r}")
            if not dims or any(d <= 0 for d in dims):
                raise SpecError(f"bad topology {topology!r}")
            per_slice = 1
            for d in dims:
                per_slice *= d
        else:
            per_slice = max(chips_per_host, 1)
        n = per_slice * max(n_slices, 1)
    else:
        n = n_devices
    if n_slices > 1 and n % n_slices == 0:
        # multi-slice gang: dcn (pure data parallel) over the slice
        # boundary, dp x tp within each slice over ICI
        per_slice = n // n_slices
        if chips_per_host and per_slice % chips_per_host == 0 \
                and per_slice >= chips_per_host:
            return MeshSpec(
                dcn=n_slices,
                dp=per_slice // chips_per_host,
                tp=chips_per_host,
            )
        if chips_per_host and per_slice % chips_per_host and topology:
            raise SpecError(
                f"TPU_CHIPS_PER_HOST={chips_per_host} does not divide "
                f"the {per_slice}-chip slice of topology {topology!r}: "
                "no host-aligned mesh exists for this spec"
            )
        return MeshSpec(dcn=n_slices, dp=per_slice)
    if chips_per_host and n % chips_per_host == 0 and n > chips_per_host:
        return MeshSpec(dp=n // chips_per_host, tp=chips_per_host)
    if chips_per_host and n % chips_per_host and topology:
        raise SpecError(
            f"TPU_CHIPS_PER_HOST={chips_per_host} does not divide the "
            f"{n} chips of topology {topology!r}: no host-aligned mesh "
            "exists for this spec"
        )
    return MeshSpec(dp=n)


def mesh_from_env(env: Dict[str, str], n_devices: Optional[int] = None) -> Mesh:
    """Build the Mesh :func:`derive` prescribes for this env contract."""
    n = n_devices if n_devices is not None else len(jax.devices())
    return make_mesh(derive(env, n))


def elastic_reshard_ok(old: MeshSpec, new: MeshSpec) -> bool:
    """True when a checkpoint written under ``old`` restores onto
    ``new`` as a pure re-layout — elastic-DP resize (ISSUE 13).

    The contract: only the batch axes (``dp``/``dcn``) may change.
    Params and optimizer state are REPLICATED over dp/dcn, so a
    changed width re-lays the same leaves; any model-sharding axis
    changing (tp/sp/pp/ep/fsdp) would change leaf SHARDS, and the
    host-gathered npz checkpoint would silently restore a different
    parallelism than the step function expects.  The worker refuses
    that resume loudly instead.

    A whole-slice drop or regrow (ISSUE 20 multi-slice elasticity)
    is exactly a dcn change — the per-slice topology, and with it
    every model axis, is untouched — so it rides this rule with no
    special case."""
    return (
        old.tp == new.tp
        and old.sp == new.sp
        and old.pp == new.pp
        and old.ep == new.ep
        and old.fsdp == new.fsdp
    )


# -- sharding rules ---------------------------------------------------

Rules = Tuple[Tuple[str, PartitionSpec], ...]


def named(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(*spec))


BATCH_AXES = ("dcn", "dp", "fsdp")  # batch shards over all data axes


def batch_spec() -> PartitionSpec:
    return PartitionSpec(BATCH_AXES, "sp")  # [batch, seq, ...]


def replicated() -> PartitionSpec:
    return PartitionSpec()
