"""X9: the fleet health plane — metric history, event journal,
anomaly detectors.

Traceview (X4) answers "what just happened"; this package retains and
judges: bounded metric history rings (metrics/registry.py
MetricHistory) behind ``GET /v1/debug/health``, a durable
capacity-bounded event journal (operator verbs, plan transitions,
failovers, admission rejections, recovery actions, detector alerts)
behind ``GET /v1/debug/events?since=``, and per-cycle detectors —
straggler median-ratio scoring off merged steplogs, serving-SLO
watchers off the engine gauges, lease-churn watching off ha.* — whose
suspect-host output feeds placement as a soft sort-last signal.

ROADMAP item 2 closed the loop (health/actions.py): detector
episodes now drive audited, flap-proof, operator-interruptible
ACTIONS — SLO-breach scale-out, quiet-pod scale-in through the
decommission step family with a pre-kill router drain, and general
straggler remediation — all riding the plan engine and seeded from
the replayed journal across failovers.
"""

from dcos_commons_tpu.health.actions import (
    ActionPolicy,
    HealthActionEngine,
    decide,
    remediation_allowed,
    scale_out_target,
    seed_latches,
)
from dcos_commons_tpu.health.detectors import (
    LeaseChurnWatcher,
    QuietPodWatcher,
    ServingSloWatcher,
    StragglerDetector,
    median_ratio_scores,
)
from dcos_commons_tpu.health.journal import (
    EventJournal,
    PersisterBackend,
    StatePropertyBackend,
)
from dcos_commons_tpu.health.monitor import HealthMonitor

__all__ = [
    "ActionPolicy",
    "EventJournal",
    "HealthActionEngine",
    "HealthMonitor",
    "LeaseChurnWatcher",
    "PersisterBackend",
    "QuietPodWatcher",
    "ServingSloWatcher",
    "StatePropertyBackend",
    "StragglerDetector",
    "decide",
    "median_ratio_scores",
    "remediation_allowed",
    "scale_out_target",
    "seed_latches",
]
