"""Multi-slice gangs over DCN (ISSUE 20).

End to end: a ``tpu: slices: 2`` gang deploys across two physical
slices with slice-major worker numbering, per-slice coordinator
anchors (TPU_SLICE_COORDS + slice-coordinator port reservations) and
a derived ICIxDCN mesh; killing a whole slice shrinks the gang onto
the surviving slice (the dcn axis drops, the per-slice topology is
untouched) and the gang regrows to declared width when the slice
returns.  Unit level: DCN-pool pinning, generation filtering, the
admission gate, the worker-side contract parse, stepcompare's DCN
wire leg, the whole-slice chaos spec, and a bit-identical fenced
checkpoint re-layout across the dcn shrink.
"""

import dataclasses

import pytest

from dcos_commons_tpu.common import TaskState, TaskStatus
from dcos_commons_tpu.offer import (
    OfferEvaluator,
    ReservationLedger,
    SliceInventory,
)
from dcos_commons_tpu.offer.inventory import make_test_fleet
from dcos_commons_tpu.offer.multislice import SLICE_COORDINATOR_PORT_NAME
from dcos_commons_tpu.plan.step import PodInstanceRequirement
from dcos_commons_tpu.specification import from_yaml
from dcos_commons_tpu.state import StateStore
from dcos_commons_tpu.storage import MemPersister
from dcos_commons_tpu.testing import (
    AdvanceCycles,
    ExpectDeploymentComplete,
    HostUp,
    PreemptHost,
    SendTaskRunning,
    ServiceTestRunner,
)

# an 8-worker gang spanning two 4-host slices (4x4 chips each),
# elastic down to one whole slice
MULTISLICE_YAML = """
name: mssvc
pods:
  trainer:
    count: 8
    gang: true
    tpu:
      generation: v5e
      chips-per-host: 4
      topology: 4x4
      slices: 2
      elastic: true
      min-hosts: 4
    tasks:
      worker:
        goal: RUNNING
        cmd: "train"
        cpus: 1.0
        memory: 256
"""

# a small 4-worker/2-slice gang for evaluator-level tests: each slice
# is 2 hosts of 2x2 chip blocks (one 2x4 sub-slice per slice)
SMALL_MS_YAML = """
name: jax
pods:
  trainer:
    count: 4
    gang: true
    tpu:
      generation: v5e
      chips-per-host: 4
      topology: 2x4
      slices: 2
    tasks:
      worker:
        goal: FINISH
        cmd: "python train.py"
        cpus: 2.0
        memory: 4096
"""


def slice_fleet(*slice_ids):
    return [h for s in slice_ids for h in make_test_fleet(slice_id=s)]


def two_host_slice(slice_id, generation="v5e", pool=""):
    hosts = make_test_fleet(
        slice_id=slice_id, host_grid=(1, 2), chip_block=(2, 2),
        generation=generation,
    )
    if pool:
        hosts = [
            dataclasses.replace(h, attributes={"dcn_pool": pool})
            for h in hosts
        ]
    return hosts


def build_eval(yaml_text, hosts):
    spec = from_yaml(yaml_text)
    persister = MemPersister()
    store = StateStore(persister)
    ledger = ReservationLedger(persister)
    ev = OfferEvaluator(store, ledger, spec.name, "cfg-1")
    inv = SliceInventory(hosts)
    return spec, store, ledger, ev, inv


def deploy_multislice(hosts):
    runner = ServiceTestRunner(MULTISLICE_YAML, hosts=hosts)
    runner.run([
        AdvanceCycles(1),
        *[SendTaskRunning(f"trainer-{i}-worker") for i in range(8)],
        ExpectDeploymentComplete(),
    ])
    return runner


def gang_hosts(scheduler):
    return {
        info.name: info.agent_id
        for info in scheduler.state_store.fetch_tasks()
    }


def slice_of(host_id):
    return host_id.rsplit("-h", 1)[0]


def ack_new_launches(world, acked):
    """RUNNING-ack every WAL'd launch whose process is still alive."""
    scheduler = world.scheduler
    for info in list(world.agent.launched):
        if info.task_id in acked:
            continue
        if info.task_id not in world.agent.active_task_ids():
            continue
        status = scheduler.state_store.fetch_status(info.name)
        if status is not None and status.task_id == info.task_id and \
                status.state is TaskState.STAGING:
            acked.add(info.task_id)
            world.agent.send(TaskStatus(
                task_id=info.task_id, state=TaskState.RUNNING,
                ready=True, agent_id=info.agent_id,
            ))


def drive_to_recovered(world, cycles=30):
    acked = set()
    for _ in range(cycles):
        world.scheduler.run_cycle()
        ack_new_launches(world, acked)
        if world.scheduler.plan("recovery").is_complete:
            return True
    return False


def recovery_verbs(scheduler):
    return [
        e.get("verb")
        for e in scheduler.journal.events(kinds=("recovery",))
    ]


# -- end-to-end deploy ------------------------------------------------


def test_multislice_deploy_env_contract_end_to_end():
    """tpu: slices: 2 deploys across two physical slices and every
    worker carries the full ICIxDCN contract: slice-major numbering,
    TPU_SLICE_COORDS anchored on each slice's first worker, a
    slice-coordinator port reservation per slice leader, and an env
    from which the mesh layer derives dcn=2."""
    from dcos_commons_tpu.parallel.mesh import derive

    runner = deploy_multislice(slice_fleet("pod-a", "pod-b", "pod-c"))
    scheduler = runner.world.scheduler
    tasks = sorted(
        scheduler.state_store.fetch_tasks(),
        key=lambda i: int(i.env["TPU_WORKER_ID"]),
    )
    assert len(tasks) == 8

    # slice-major: workers 0-3 share one slice, 4-7 another
    slices = [slice_of(i.agent_id) for i in tasks]
    assert len(set(slices[:4])) == 1 and len(set(slices[4:])) == 1
    assert slices[0] != slices[4]
    for i, info in enumerate(tasks):
        assert info.env["TPU_SLICE_INDEX"] == str(i // 4)
        assert info.env["TPU_NUM_SLICES"] == "2"
        assert info.env["TPU_HOSTS_PER_SLICE"] == "4"
        assert info.env["TPU_WORKER_COUNT"] == "8"

    # per-slice coordinator anchors: one address per slice, anchored
    # on that slice's first worker, identical for every worker
    coords = {i.env["TPU_SLICE_COORDS"] for i in tasks}
    assert len(coords) == 1
    entries = coords.pop().split(",")
    assert len(entries) == 2
    for k, entry in enumerate(entries):
        leader = tasks[k * 4]
        assert entry.split(":")[0] == leader.agent_id

    # the rendezvous port is a real reservation on each slice leader
    anchors = [
        r for r in scheduler.ledger.all()
        if r.container_path == SLICE_COORDINATOR_PORT_NAME
    ]
    assert sorted(r.host_id for r in anchors) == sorted(
        [tasks[0].agent_id, tasks[4].agent_id]
    )

    # the worker derives the dcn axis from this exact env
    mesh = derive(dict(tasks[0].env))
    assert (mesh.dcn, mesh.dp, mesh.tp) == (2, 4, 4)


# -- whole-slice elasticity -------------------------------------------


def test_whole_slice_shrink_then_regrow():
    """Killing one slice of a 2-slice elastic gang (with no spare
    capacity anywhere) shrinks the gang onto the surviving slice —
    per-slice topology untouched, dcn axis dropped, surplus trimmed,
    zero claims left on the dead slice — and the gang regrows to
    declared width when the slice returns."""
    runner = deploy_multislice(slice_fleet("pod-a", "pod-b"))
    world = runner.world
    scheduler = world.scheduler
    placed = gang_hosts(scheduler)
    victim_slice = slice_of(placed["trainer-0-worker"])
    victims = sorted(
        a for a in set(placed.values()) if slice_of(a) == victim_slice
    )
    assert len(victims) == 4

    runner.run([PreemptHost(h) for h in victims])
    assert drive_to_recovered(world)

    # shrunk to ONE whole slice on the survivor
    after = gang_hosts(scheduler)
    assert sorted(after) == [f"trainer-{i}-worker" for i in range(4)]
    assert {slice_of(a) for a in after.values()} == {
        s for s in ("pod-a", "pod-b") if s != victim_slice
    }
    for name in ("trainer-4-worker", "trainer-7-worker"):
        assert scheduler.state_store.fetch_task(name) is None
    envs = [i.env for i in scheduler.state_store.fetch_tasks()]
    for env in envs:
        # the slice keeps its full per-slice shape; only dcn dropped
        assert env["TPU_TOPOLOGY"] == "4x4"
        assert env["TPU_WORKER_COUNT"] == "4"
        assert "TPU_NUM_SLICES" not in env
        assert "TPU_SLICE_COORDS" not in env
    # zero claims survive on the dead slice
    for h in victims:
        assert not [r for r in scheduler.ledger.all() if r.host_id == h]
    verbs = recovery_verbs(scheduler)
    assert "elastic-shrink" in verbs and "trim-surplus" in verbs

    # the slice comes back -> regrow to declared width
    runner.run([HostUp(h) for h in victims])
    acked = set()
    for _ in range(40):
        scheduler.run_cycle()
        ack_new_launches(world, acked)
        if len(scheduler.state_store.fetch_tasks()) == 8 and \
                scheduler.plan("recovery").is_complete:
            break
    regrown = sorted(
        scheduler.state_store.fetch_tasks(),
        key=lambda i: int(i.env["TPU_WORKER_ID"]),
    )
    assert len(regrown) == 8
    assert {slice_of(i.agent_id) for i in regrown} == {"pod-a", "pod-b"}
    for info in regrown:
        assert info.env["TPU_NUM_SLICES"] == "2"
        assert info.env["TPU_WORKER_COUNT"] == "8"
    assert "elastic-regrow" in recovery_verbs(scheduler)


def test_shrunken_gang_survives_scheduler_restart_then_regrows():
    """Scheduler restart while a multi-slice gang is elastically
    shrunken must not deadlock.  The restart-rebuilt update plan sees
    tasks 0..3 at target config and 4..7 missing; seeding that clean
    suffix hole as PENDING would leave a full-width gang step that can
    never place (the survivors hold their slice's reservations) while
    blocking the recovery manager's regrow scan as externally managed.
    The surviving prefix seeds COMPLETE instead, and regrow fires when
    the slice returns."""
    hosts = slice_fleet("pod-a", "pod-b")
    runner = deploy_multislice(hosts)
    world = runner.world
    placed = gang_hosts(world.scheduler)
    victim_slice = slice_of(placed["trainer-0-worker"])
    victims = sorted(
        a for a in set(placed.values()) if slice_of(a) == victim_slice
    )
    runner.run([PreemptHost(h) for h in victims])
    assert drive_to_recovered(world)
    assert len(world.scheduler.state_store.fetch_tasks()) == 4

    # restart: same persister + agent (the shrunken gang keeps
    # running), fresh scheduler
    runner2 = runner.restart()
    world2 = runner2.build()
    scheduler = world2.scheduler
    assert len(scheduler.state_store.fetch_tasks()) == 4
    # the rebuilt plan re-derives COMPLETE from the shrunken prefix
    scheduler.run_cycle()
    assert scheduler.plan("update").is_complete

    # the slice comes back -> the recovery manager regrows
    runner2.run([HostUp(h) for h in victims])
    acked = set()
    for _ in range(40):
        scheduler.run_cycle()
        ack_new_launches(world2, acked)
        if len(scheduler.state_store.fetch_tasks()) == 8 and \
                scheduler.plan("recovery").is_complete:
            break
    regrown = scheduler.state_store.fetch_tasks()
    assert len(regrown) == 8
    assert {slice_of(i.agent_id) for i in regrown} == {"pod-a", "pod-b"}
    assert all(i.env["TPU_NUM_SLICES"] == "2" for i in regrown)
    assert "elastic-regrow" in recovery_verbs(scheduler)


# -- slice-set placement rules ----------------------------------------


def test_multislice_gang_pins_one_dcn_pool():
    """Slices on different DCN fabrics cannot form one gang: the
    first sub-slice pins the pool, the rest must match, and two free
    slices on one fabric win over a free slice on another."""
    fleet = (
        two_host_slice("pod-a", pool="fabric-1")
        + two_host_slice("pod-b", pool="fabric-1")
        + two_host_slice("pod-z", pool="fabric-2")
    )
    spec, store, ledger, ev, inv = build_eval(SMALL_MS_YAML, fleet)
    result = ev.evaluate(
        PodInstanceRequirement(
            pod=spec.pod("trainer"), instances=[0, 1, 2, 3]
        ),
        inv,
    )
    assert result.passed, result.outcome.flatten()
    placed = {inv.host(i.agent_id).slice_id for i in result.task_infos}
    assert placed == {"pod-a", "pod-b"}

    # one free slice per fabric: the gang must refuse, naming the pool
    split = (
        two_host_slice("pod-a", pool="fabric-1")
        + two_host_slice("pod-b", pool="fabric-2")
    )
    spec, store, ledger, ev, inv = build_eval(SMALL_MS_YAML, split)
    result = ev.evaluate(
        PodInstanceRequirement(
            pod=spec.pod("trainer"), instances=[0, 1, 2, 3]
        ),
        inv,
    )
    assert not result.passed
    assert "on dcn pool fabric" in result.outcome.reason


def test_multislice_gang_filters_by_generation():
    """Slice-set placement only sees slices of the spec's generation —
    the same fact admission and regrow sizing count — so a v5p gang
    skips free v5e slices instead of landing on the wrong silicon."""
    fleet = (
        two_host_slice("pod-old", generation="v5e")
        + two_host_slice("pod-p1", generation="v5p")
        + two_host_slice("pod-p2", generation="v5p")
    )
    yaml_text = SMALL_MS_YAML.replace(
        "generation: v5e", "generation: v5p"
    )
    spec, store, ledger, ev, inv = build_eval(yaml_text, fleet)
    result = ev.evaluate(
        PodInstanceRequirement(
            pod=spec.pod("trainer"), instances=[0, 1, 2, 3]
        ),
        inv,
    )
    assert result.passed, result.outcome.flatten()
    placed = {inv.host(i.agent_id).slice_id for i in result.task_infos}
    assert placed == {"pod-p1", "pod-p2"}


# -- admission gate ---------------------------------------------------


def test_admission_multislice_chip_span_mismatch():
    from dcos_commons_tpu.multi.admission import validate_service_yaml

    bad = MULTISLICE_YAML.replace("count: 8", "count: 4")
    spec, findings = validate_service_yaml(bad, "mssvc")
    multi = [f for f in findings if f.rule == "multislice"]
    assert multi, findings
    assert "spans 16 chip(s)" in multi[0].message
    assert multi[0].line > 1  # anchored at the pod, not the file


def test_admission_multislice_fleet_sizing():
    from dcos_commons_tpu.multi.admission import validate_service_yaml

    # one registered v5e slice cannot host a 2-slice gang
    spec, findings = validate_service_yaml(
        MULTISLICE_YAML, "mssvc",
        inventory=SliceInventory(make_test_fleet("pod-a")),
    )
    multi = [f for f in findings if f.rule == "multislice"]
    assert multi and "registers only 1" in multi[0].message

    # two registered slices admit it
    spec, findings = validate_service_yaml(
        MULTISLICE_YAML, "mssvc",
        inventory=SliceInventory(slice_fleet("pod-a", "pod-b")),
    )
    assert not [f for f in findings if f.rule == "multislice"], findings

    # scheduler bootstrap (no inventory): sizing is skipped, never
    # rejected against an empty fleet
    spec, findings = validate_service_yaml(MULTISLICE_YAML, "mssvc")
    assert not [f for f in findings if f.rule == "multislice"], findings


# -- worker-side contract ---------------------------------------------


def test_initialize_from_env_parses_slice_contract():
    """The bootstrap shim parses the multi-slice env contract and
    picks this worker's own slice anchor; no COORDINATOR_ADDRESS means
    no jax.distributed call, so the parse is testable in isolation."""
    from dcos_commons_tpu.parallel.distributed import initialize_from_env

    contract = initialize_from_env({
        "TPU_WORKER_ID": "5", "TPU_WORKER_COUNT": "8",
        "TPU_CHIPS_PER_HOST": "4", "TPU_TOPOLOGY": "4x4",
        "TPU_NUM_SLICES": "2", "TPU_SLICE_INDEX": "1",
        "TPU_HOSTS_PER_SLICE": "4",
        "TPU_SLICE_COORDS": "pod-a-h0-0:12001,pod-b-h0-0:12001",
    })
    assert contract["num_slices"] == 2
    assert contract["slice_index"] == 1
    assert contract["hosts_per_slice"] == 4
    assert contract["slice_coords"] == [
        "pod-a-h0-0:12001", "pod-b-h0-0:12001",
    ]
    assert contract["slice_coordinator"] == "pod-b-h0-0:12001"

    # an out-of-range index degrades to "" instead of raising
    broken = initialize_from_env({
        "TPU_NUM_SLICES": "2", "TPU_SLICE_INDEX": "7",
        "TPU_SLICE_COORDS": "a:1,b:2",
    })
    assert broken["slice_coordinator"] == ""


def test_stepcompare_prices_the_dcn_leg():
    """The wire floor takes the cheaper spelling PER AXIS and reports
    the DCN share separately (the leg the slow fabric explains)."""
    from dcos_commons_tpu.analysis.shardcheck import stepcompare

    cost = {"per_step": [
        {"axis": "dcn", "ring_us": 100.0, "allgather_us": 150.0},
        {"axis": "dp", "ring_us": 30.0, "allgather_us": 20.0},
    ]}
    out = stepcompare(cost, [])
    assert out["predicted_wire_us"] == 120.0
    assert out["predicted_wire_dcn_us"] == 100.0
    assert out["predicted_floor_us"] == 120.0


# -- whole-slice chaos ------------------------------------------------


def test_storm_whole_slice_kill_converges():
    """A whole-slice PreemptSpec kills EVERY host of one gang slice
    physically (statuses never arrive); with a spare slice available
    the gang converges back to full width under the storm invariants
    (exactly one incarnation, slice-aligned workers, no claims on the
    dead slice)."""
    from dcos_commons_tpu.testing.chaos import (
        CHAOS_MULTISLICE_YAML,
        STORM_START,
        PreemptSpec,
        PreemptionStorm,
    )

    storm = PreemptionStorm(
        [PreemptSpec(at=STORM_START, hosts=1, whole_slice=True)],
        yaml_text=CHAOS_MULTISLICE_YAML,
        hosts=slice_fleet("gang-a", "gang-b", "gang-c"),
    )
    try:
        report = storm.run(timeout_s=120.0)
    finally:
        storm.shutdown()
    assert report.converged
    # hosts=1 means ONE SLICE: all four of its hosts die together
    assert len(report.preempted) == 4
    assert len({slice_of(h) for h in report.preempted}) == 1


# -- fenced-checkpoint re-layout across the dcn shrink ----------------


def test_dcn_shrink_restore_is_bit_identical_and_deterministic():
    """A checkpoint written on the 2-slice mesh (dcn=2, dp=2, tp=2)
    restores onto the 1-slice mesh (dp=2, tp=2) bit-identically —
    dropping dcn is a pure re-layout — and the resumed run is
    deterministic: two resumes from the same fenced checkpoint
    produce the same loss sequence.  Runs on the 8 forced CPU
    devices conftest provides."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from dcos_commons_tpu.models import (
        config_from_env,
        init_params,
        make_train_step,
    )
    from dcos_commons_tpu.parallel.mesh import (
        MeshSpec,
        elastic_reshard_ok,
        make_mesh,
    )
    from dcos_commons_tpu.utils import (
        restore_checkpoint,
        save_checkpoint,
        synthetic_tokens,
    )

    devices = jax.devices()
    if len(devices) < 8:
        pytest.skip("needs 8 forced host devices")
    # the resize rule agrees this is a pure re-layout
    assert elastic_reshard_ok(
        MeshSpec(dcn=2, dp=2, tp=2), MeshSpec(dp=2, tp=2)
    )

    config = config_from_env(
        {"D_MODEL": "32", "N_LAYERS": "1", "N_HEADS": "2",
         "N_KV_HEADS": "2", "D_FF": "64", "VOCAB": "64",
         "SEQ_LEN": "16"},
        dtype=jnp.float32,
    )
    optimizer = optax.adamw(1e-3)
    tokens, targets = synthetic_tokens(
        jax.random.key(1), 8, config.max_seq, config.vocab
    )

    import tempfile

    ckpt = tempfile.mkdtemp(prefix="dcn-shrink-ckpt-")
    mesh8 = make_mesh(MeshSpec(dcn=2, dp=2, tp=2), devices=devices[:8])
    with mesh8:
        params = init_params(config, jax.random.key(0))
        opt_state = optimizer.init(params)
        step_fn = make_train_step(config, optimizer, mesh=mesh8)
        for _ in range(3):
            params, opt_state, _loss = step_fn(
                params, opt_state, tokens, targets
            )
        state8 = {"params": params, "opt_state": opt_state}
        save_checkpoint(ckpt, 3, state8)
        flat8 = [np.asarray(x) for x in jax.tree.leaves(state8)]

    mesh4 = make_mesh(MeshSpec(dp=2, tp=2), devices=devices[:4])

    def resume(junk_seed):
        with mesh4:
            junk = init_params(config, jax.random.key(junk_seed))
            like = {"params": junk, "opt_state": optimizer.init(junk)}
            restored, step = restore_checkpoint(ckpt, like)
            assert step == 3
            # materialize BEFORE training: the step function may
            # donate its inputs, invalidating the restored buffers
            flat = [np.asarray(x) for x in jax.tree.leaves(restored)]
            step_fn4 = make_train_step(config, optimizer, mesh=mesh4)
            p, o = restored["params"], restored["opt_state"]
            losses = []
            for _ in range(3):
                p, o, loss = step_fn4(p, o, tokens, targets)
                losses.append(float(loss))
            return flat, losses

    flat4, losses_a = resume(7)
    assert len(flat4) == len(flat8)
    for a, b in zip(flat8, flat4):
        assert np.array_equal(a, b), \
            "dcn shrink restore must be bit-identical"
    _flat, losses_b = resume(11)
    assert losses_a == losses_b
    assert all(np.isfinite(v) for v in losses_a)
