"""tpu-service-sdk: a TPU-native service-orchestration framework.

A ground-up rebuild of the capabilities of the DC/OS Commons SDK
(reference: /root/reference, surveyed in SURVEY.md) for TPU fleets:

- declarative YAML ServiceSpecs compiled into plan-driven deployments
  (deploy / update / recovery / decommission / uninstall as
  Plan -> Phase -> Step state machines with serial/parallel/canary/
  dependency rollout strategies),
- a crash-safe control plane (write-ahead state store, config-diff
  rolling updates, placement rules, health/readiness checks),
- a TPU **slice inventory** replacing Mesos resource offers: hosts,
  chips and ICI torus coordinates are first-class schedulable
  resources, and placement constraints encode torus adjacency,
- gang-scheduled multi-host `jax.pjit` pods as the flagship workload
  (models/, ops/, parallel/ subpackages), rendezvoused through a
  scheduler-issued coordinator address,
- an HTTP API + CLI with the reference's verb set, and a no-cluster
  simulation test harness.

Layer map (mirrors SURVEY.md section 1):
    storage/        L5  KV persistence (reference: sdk/scheduler .../storage/)
    state/          L5  task/config/framework state (.../state/)
    specification/  L4  typed service specs + YAML (.../specification/)
    plan/           L2  plan engine + strategies (.../scheduler/plan/)
    offer/          L3  slice snapshots + evaluation + placement (.../offer/)
    recovery/       L2  failure recovery (.../scheduler/recovery/)
    decommission/   L2  scale-down plans (.../scheduler/decommission/)
    uninstall/      L2  teardown plans (.../scheduler/uninstall/)
    multi/          L2  multi-service multiplexing (.../scheduler/multi/)
    scheduler/      L2  core scheduler + builder (.../scheduler/)
    runtime/        L1  event loop, reconciler, task killer (.../framework/)
    agent/          T1  per-host agent / sandbox bootstrap (sdk/bootstrap/)
    http/           L6  REST API (.../http/)
    cli/            T2  operator CLI (cli/)
    metrics/        X3  counters + Prometheus/StatsD (.../metrics/)
    debug/          X3  offer-outcome / plan / status trackers (.../debug/)
    testing/        T3  sim harness + integration helpers (sdk/testing/)
    models/ ops/ parallel/ utils/   the TPU workload library (new; the
                    reference has no data plane - SURVEY.md section 2.2)
"""

__version__ = "0.1.0"
