"""Property-based tests for the ``{{VAR:-default}}`` template grammar.

configcheck's whole YAML side rests on one claim: the pairs
``template_occurrences`` parses out of a spec are EXACTLY what
``render_template`` would substitute — same variable, same default,
one left-to-right pass.  These properties pin that agreement (and the
deliberately non-recursive nested-default behavior) over random
identifiers and default strings, the same hypothesis-importorskip
pattern as tests/test_shard_properties.py.
"""

import string

import pytest

pytest.importorskip(
    "hypothesis", reason="property tests need the hypothesis package"
)
from hypothesis import given, strategies as st  # noqa: E402

from dcos_commons_tpu.analysis.configcheck import (  # noqa: E402
    template_occurrences,
)
from dcos_commons_tpu.specification.specs import SpecError  # noqa: E402
from dcos_commons_tpu.specification.yaml_spec import (  # noqa: E402
    _truthy,
    render_template,
)

# template names follow the renderer's grammar [A-Za-z_][A-Za-z0-9_]*
NAMES = st.from_regex(r"[A-Za-z_][A-Za-z0-9_]{0,15}", fullmatch=True)
# default/value strings: anything brace-free renders literally; '#'
# is excluded because the PARSER strips YAML comment tails, and
# whitespace is excluded so the round-trip is not confounded by the
# comment-strip's "space before #" rule
SAFE = st.text(
    alphabet=string.ascii_letters + string.digits + "._-/+:=,@",
    max_size=24,
)


@given(NAMES, SAFE, SAFE)
def test_render_parse_round_trip(name, value, default):
    """The (var, default) pair the parser extracts predicts the
    renderer byte-for-byte: default when unset, env value when set."""
    line = f'KEY: "{{{{{name}:-{default}}}}}"'
    occs = template_occurrences([line])
    assert occs == [(name, default, 1, "var")]
    template = f"{{{{{name}:-{default}}}}}"
    assert render_template(template, {}) == default
    assert render_template(template, {name: value}) == value


@given(NAMES, SAFE)
def test_empty_default_renders_empty(name, value):
    """``{{VAR:-}}`` is the 'optional, defaults to empty' idiom
    (svc_serve.yml SERVE_SLOTS): unset renders "", set renders the
    value, and the parser reports the default as '' — distinct from
    the None of a defaultless ``{{VAR}}``."""
    template = f"{{{{{name}:-}}}}"
    assert render_template(template, {}) == ""
    assert render_template(template, {name: value}) == value
    occs = template_occurrences([template])
    assert occs == [(name, "", 1, "var")]
    bare = template_occurrences([f"{{{{{name}}}}}"])
    assert bare == [(name, None, 1, "var")]


@given(NAMES, NAMES, SAFE)
def test_nested_default_is_single_pass(outer, inner, default):
    """Defaults substitute in ONE left-to-right pass and are never
    re-expanded: ``{{A:-{{B:-x}}}}`` with A unset leaves the literal
    inner template text, not x — the regex's ``[^}]*`` default stops
    at the first brace, so nesting is (deliberately) not a feature.
    The parser agrees, reporting the same truncated default."""
    template = f"{{{{{outer}:-{{{{{inner}:-{default}}}}}}}}}"
    rendered = render_template(template, {})
    assert rendered == f"{{{{{inner}:-{default}}}}}"
    # the inner template text survives VERBATIM — a second render
    # would substitute it, proving nothing recursed the first time
    assert render_template(rendered, {}) == default
    occs = template_occurrences([template])
    assert occs[0][:2] == (outer, f"{{{{{inner}:-{default}")


@given(NAMES)
def test_missing_defaultless_var_raises(name):
    """A defaultless ``{{VAR}}`` with no env value fails the render
    loudly, naming the variable (TemplateUtils semantics)."""
    with pytest.raises(SpecError) as err:
        render_template(f"{{{{{name}}}}}", {})
    assert name in str(err.value)


@given(NAMES, SAFE, SAFE)
def test_section_visibility_matches_truthy(name, body, value):
    """``{{#VAR}}body{{/VAR}}`` keeps the body exactly when _truthy
    says so, and ``{{^VAR}}`` is its complement."""
    pos = f"{{{{#{name}}}}}{body}{{{{/{name}}}}}"
    neg = f"{{{{^{name}}}}}{body}{{{{/{name}}}}}"
    env = {name: value}
    assert render_template(pos, env) == (
        body if _truthy(value) else ""
    )
    assert render_template(neg, env) == (
        "" if _truthy(value) else body
    )
