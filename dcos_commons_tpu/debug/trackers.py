"""Debug trackers backing /v1/debug/*.

Reference: debug/OfferOutcomeTrackerV2.java (ring buffer of evaluation
outcomes), debug/PlansTracker.java, debug/TaskStatusesTracker.java,
debug/TaskReservationsTracker.java.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Dict, List

from dcos_commons_tpu.offer.outcome import EvaluationOutcome


class OfferOutcomeTracker:
    """Ring buffer of per-requirement evaluation outcomes."""

    def __init__(self, capacity: int = 100):
        self._buffer = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()

    def record(self, requirement_name: str, outcome: EvaluationOutcome) -> None:
        with self._lock:
            self._buffer.append(
                {
                    "timestamp": time.time(),
                    "requirement": requirement_name,
                    "passed": outcome.passed,
                    "outcome": outcome.to_dict(),
                    "explanation": outcome.flatten(),
                }
            )

    def to_json(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._buffer)


class PlansTracker:
    """Serialized view of every plan tree (reference: PlansTracker)."""

    def __init__(self, scheduler):
        self._scheduler = scheduler

    def to_json(self) -> Dict[str, Any]:
        out = {}
        for name, plan in self._scheduler.plans().items():
            out[name] = serialize_plan(plan)
        return out


class TaskStatusesTracker:
    def __init__(self, state_store):
        self._state_store = state_store

    def to_json(self) -> List[Dict[str, Any]]:
        out = []
        for name, status in sorted(self._state_store.fetch_statuses().items()):
            out.append(
                {
                    "name": name,
                    "task_id": status.task_id,
                    "state": status.state.value,
                    "ready": status.ready,
                    "message": status.message,
                    "agent_id": status.agent_id,
                }
            )
        return out


class TaskReservationsTracker:
    def __init__(self, ledger):
        self._ledger = ledger

    def to_json(self) -> List[Dict[str, Any]]:
        return [r.to_dict() for r in self._ledger.all()]


def serialize_plan(plan) -> Dict[str, Any]:
    # plan-level errors aggregate every element's (reference:
    # PlansQueries surfacing step errors in the plan body — the
    # operator must see WHY a step is ERROR without spelunking)
    errors = list(plan.errors)
    for phase in plan.phases:
        errors.extend(phase.errors)
        for step in phase.steps:
            errors.extend(step.errors)
    return {
        "name": plan.name,
        "status": plan.get_status().value,
        "errors": errors,
        "phases": [
            {
                "id": phase.id,
                "name": phase.name,
                "status": phase.get_status().value,
                "steps": [
                    {
                        "id": step.id,
                        "name": step.name,
                        "status": step.get_status().value,
                        "assets": sorted(step.get_asset_names()),
                    }
                    for step in phase.steps
                ],
            }
            for phase in plan.phases
        ],
    }
