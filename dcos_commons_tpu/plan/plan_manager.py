"""PlanManager: owns one plan — candidates + status routing.

Reference: scheduler/plan/PlanManager.java:14-42,
DefaultPlanManager.java.
"""

from __future__ import annotations

from typing import List, Set

from dcos_commons_tpu.common import TaskStatus
from dcos_commons_tpu.plan.plan import Plan
from dcos_commons_tpu.plan.step import Step


class PlanManager:
    def get_plan(self) -> Plan:
        raise NotImplementedError

    def get_candidates(self, dirty_assets: Set[str]) -> List[Step]:
        raise NotImplementedError

    def update(self, status: TaskStatus) -> None:
        raise NotImplementedError

    def set_transition_listener(self, listener) -> None:
        """Attach the traceview step-transition hook to every step this
        manager currently owns.  Managers that mint steps dynamically
        (recovery) are covered because the scheduler re-wires at the
        top of every cycle, before statuses route."""
        for step in self.get_plan().all_steps():
            step.transition_listener = listener

    def in_progress_assets(self) -> Set[str]:
        """Assets of steps currently holding resources mid-transition;
        used by the coordinator for mutual exclusion."""
        assets: Set[str] = set()
        for step in self.get_plan().all_steps():
            if step.get_status().is_running:
                assets |= step.get_asset_names()
        return assets


class DefaultPlanManager(PlanManager):
    """Reference: plan/DefaultPlanManager.java — wraps a static plan."""

    def __init__(self, plan: Plan):
        self._plan = plan

    def get_plan(self) -> Plan:
        return self._plan

    def get_candidates(self, dirty_assets: Set[str]) -> List[Step]:
        if self._plan.is_complete:
            return []
        return self._plan.candidates(dirty_assets)

    def update(self, status: TaskStatus) -> None:
        self._plan.update(status)
