"""Inference serving task: the flagship behind an HTTP endpoint.

The scheduler deploys this like any other task (svc_serve.yml): it
builds the model, warms the KV-cache generate path (one compile), then
serves POST /generate on the scheduler-assigned port — discoverable
via /v1/endpoints and the VIP.  Readiness: the task's readiness check
passes once the warmup file exists, so the deploy plan completes only
when the server can actually answer.

Request:  {"tokens": [[...]], "max_new_tokens": N, "temperature": T}
Response: {"tokens": [[...]]} — the continuations only.
"""

import json
import os
import sys
import threading

import numpy as np
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

sys.path.insert(0, os.environ.get("REPO_ROOT", "/root/repo"))


def main() -> int:
    import jax
    import jax.numpy as jnp

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")

    from dcos_commons_tpu.models import (
        TransformerConfig,
        generate,
        init_params,
    )
    from dcos_commons_tpu.utils import (
        enable_compilation_cache,
        restore_checkpoint,
    )

    enable_compilation_cache()
    config = TransformerConfig(
        vocab=int(os.environ.get("VOCAB", "8192")),
        d_model=int(os.environ.get("D_MODEL", "512")),
        n_layers=int(os.environ.get("N_LAYERS", "4")),
        n_heads=8,
        n_kv_heads=8,
        d_ff=int(os.environ.get("D_FF", "1408")),
        max_seq=int(os.environ.get("SEQ_LEN", "1024")),
        dtype=jnp.bfloat16 if os.environ.get(
            "JAX_PLATFORMS"
        ) != "cpu" else jnp.float32,
        remat=False,
    )
    max_len = int(os.environ.get("MAX_LEN", "256"))
    batch = int(os.environ.get("SERVE_BATCH", "1"))
    new_tokens = int(os.environ.get("MAX_NEW_TOKENS", "32"))

    params = init_params(config, jax.random.key(0))
    ckpt_dir = os.environ.get("CHECKPOINT_DIR", "")
    if ckpt_dir:
        # serve the TRAINED weights when a checkpoint tree exists
        # (the train pod's orbax-style output); params-only restore
        state, step = restore_checkpoint(ckpt_dir, {"params": params})
        if step is not None:
            params = state["params"]
            print(f"restored checkpoint step {step}", flush=True)

    # ONE compile covers every request: static (batch, prompt_len)
    # shapes with prompts RIGHT-padded and the true length TRACED
    # (causal attention means real tokens never see the padding, and
    # decode overwrites/masks the pad slots); temperature is a traced
    # operand too — novel temperatures must not recompile
    prompt_len = max_len - new_tokens
    # KV_DTYPE=int8 halves the cache bytes per decode step: the lever
    # for large serving batches on a full chip (models/decode.py)
    kv_dtype = os.environ.get("KV_DTYPE", "native")
    gen = jax.jit(lambda p, t, key, temp, n: generate(
        config, p, t, max_new_tokens=new_tokens, max_len=max_len,
        temperature=temp, key=key, true_len=n, kv_dtype=kv_dtype,
    ))
    lock = threading.Lock()

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):
            pass

        def do_POST(self):
            if self.path != "/generate":
                self.send_error(404)
                return
            length = int(self.headers.get("Content-Length", 0))
            try:
                body = json.loads(self.rfile.read(length))
                rows = body["tokens"]
                if len(rows) > batch:
                    raise ValueError(
                        f"{len(rows)} prompts > server batch {batch}; "
                        "split the request"
                    )
                lens = {len(row) for row in rows}
                if len(lens) > 1:
                    raise ValueError(
                        "all prompts in one request must share a length"
                    )
                true_len = max(lens, default=0)
                if true_len < 1:
                    raise ValueError("prompts must be non-empty")
                if true_len > prompt_len:
                    # refuse, don't silently continue a DIFFERENT
                    # (truncated) prompt
                    raise ValueError(
                        f"prompt length {true_len} exceeds the server's "
                        f"context {prompt_len}"
                    )
                temp = float(body.get("temperature", 0.0))
                n = int(body.get("max_new_tokens", new_tokens))
                if n < 1:
                    raise ValueError(
                        f"max_new_tokens must be >= 1, got {n}"
                    )
                n = min(n, new_tokens)
                padded = jnp.zeros((batch, prompt_len), jnp.int32)
                for i, row in enumerate(rows):
                    row = [int(t) % config.vocab for t in row]
                    # RIGHT-pad: real tokens first, pads after (causal
                    # attention never lets real positions see them)
                    padded = padded.at[i, : len(row)].set(
                        jnp.asarray(row, jnp.int32)
                    )
                # fresh entropy per request: hashing only the prompt
                # made temperature>0 replies deterministic per process
                seed = int.from_bytes(os.urandom(4), "little")
                with lock:  # one generate at a time per chip
                    out = gen(
                        params, padded,
                        jax.random.key(seed),
                        jnp.float32(temp),
                        jnp.int32(true_len),
                    )
                # ONE bulk device->host fetch, then slice in numpy:
                # per-element int(out[i, j]) would be a separate
                # transfer each (~100ms over a TPU relay — 256 of
                # them turned a 1.5s generate into a 36s reply)
                host_out = np.asarray(jax.device_get(out))
                reply = {
                    "tokens": [
                        [int(t) for t in host_out[i, :n]]
                        for i in range(len(rows))
                    ]
                }
                payload = json.dumps(reply).encode()
                self.send_response(200)
            except Exception as e:  # noqa: BLE001 — surface to client
                payload = json.dumps({"error": str(e)}).encode()
                self.send_response(400)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

    # a RELAUNCH reuses the sandbox: a stale ready file from the
    # previous incarnation must not pass readiness while we are cold
    try:
        os.remove("ready")
    except OSError:
        pass
    # bind BEFORE warming and only then write the readiness file — a
    # bind failure (port collision) must fail readiness, not pass it
    port = int(os.environ.get("PORT_HTTP", "0"))
    server = ThreadingHTTPServer(("0.0.0.0", port), Handler)
    warm = jnp.zeros((batch, prompt_len), jnp.int32)
    out = gen(
        params, warm, jax.random.key(0), jnp.float32(0.0),
        jnp.int32(prompt_len),
    )
    jax.block_until_ready(out)
    with open("ready", "w") as f:
        f.write("warm\n")
    print(
        f"warm: serving generate({batch}x{prompt_len}->{new_tokens}) "
        f"on {server.server_address[1]}",
        flush=True,
    )
    server.serve_forever()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
