"""MNIST-scale MLP: the BASELINE.json config-3 workload.

The frameworks/jax single-host demo task trains this on one chip; it
exists to prove the control plane launches real JAX work, not to be
clever.  bf16 matmuls, f32 loss.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class MlpConfig:
    d_in: int = 784
    d_hidden: int = 512
    d_out: int = 10
    dtype: Any = jnp.bfloat16


def mlp_init(config: MlpConfig, key: jax.Array) -> Dict[str, jax.Array]:
    k1, k2, k3 = jax.random.split(key, 3)

    def normal(key, shape, scale):
        return (jax.random.normal(key, shape, jnp.float32) * scale).astype(
            config.dtype
        )

    return {
        "w1": normal(k1, (config.d_in, config.d_hidden), config.d_in ** -0.5),
        "b1": jnp.zeros((config.d_hidden,), config.dtype),
        "w2": normal(k2, (config.d_hidden, config.d_hidden),
                     config.d_hidden ** -0.5),
        "b2": jnp.zeros((config.d_hidden,), config.dtype),
        "w3": normal(k3, (config.d_hidden, config.d_out),
                     config.d_hidden ** -0.5),
        "b3": jnp.zeros((config.d_out,), config.dtype),
    }


def mlp_forward(params: Dict[str, jax.Array], x: jax.Array) -> jax.Array:
    x = x.astype(params["w1"].dtype)
    x = jax.nn.relu(x @ params["w1"] + params["b1"])
    x = jax.nn.relu(x @ params["w2"] + params["b2"])
    return (x @ params["w3"] + params["b3"]).astype(jnp.float32)


def mlp_loss(params, x, y):
    logits = mlp_forward(params, x)
    logp = jax.nn.log_softmax(logits)
    return -jnp.take_along_axis(logp, y[:, None], axis=-1).mean()


def mlp_train_step(optimizer):
    @jax.jit
    def step(params, opt_state, x, y):
        loss, grads = jax.value_and_grad(mlp_loss)(params, x, y)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = jax.tree.map(lambda p, u: p + u.astype(p.dtype), params, updates)
        return params, opt_state, loss

    return step
