"""Placement rules: which hosts may a pod instance land on.

Reference: offer/evaluate/placement/ (38 classes, SURVEY.md section
2.1): And/Or/Not combinators, Hostname/Attribute/Region/ZoneRule,
TaskTypeRule colocate/avoid, MaxPerHostname/Zone/Region/Attribute,
RoundRobinByHostname/Zone, string matchers Exact/Regex/Any, and
MarathonConstraintParser for the JSON dialect
(`[["hostname", "UNIQUE"]]`, GROUP_BY, CLUSTER, LIKE/UNLIKE, MAX_PER,
IS) accepted in the YAML ``placement:`` field.

TPU-first vocabulary additions: ``same-slice`` (all instances of a
gang pod on one physical slice — ICI never crosses slices) and
``generation:v5e`` (TPU generation match).  Torus *adjacency* is not a
per-host rule — contiguity of the selected host set is enforced by
offer/torus.py during gang evaluation.
"""

from __future__ import annotations

import json
import math
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from dcos_commons_tpu.common import TaskInfo
from dcos_commons_tpu.offer.inventory import (
    HostIndex,
    ResourceSnapshot,
    TpuHost,
    host_field,
)
from dcos_commons_tpu.offer.outcome import EvaluationOutcome


@dataclass
class PlacementContext:
    """What rules may consult: the other tasks and the host map.

    Reference: PlacementRule.filter(offer, allTasks) — rules see every
    launched task so they can count/colocate/avoid.

    Rules call ``tasks_of_pod``/``count_on``/``field_values`` once per
    candidate host per instance, so all three memoize their scans
    (they are pure in ``existing_tasks``/``hosts``).  Task additions
    mid-evaluation MUST go through ``record_tasks`` — it invalidates
    the task-derived memos; mutating ``existing_tasks`` in place after
    the first rule ran would serve stale counts.

    Fleet-scale path: when ``task_index`` (pod_type -> instance-key ->
    task list, built once per cycle by EvaluationContext) is supplied,
    per-pod instance lists and counts come from the index — no
    per-requirement scan over the fleet's whole task list.
    ``excluded_names`` are the requirement's own tasks (a relaunch
    must not block its own placement).
    """

    pod_type: str
    existing_tasks: List[TaskInfo] = field(default_factory=list)
    hosts: Dict[str, TpuHost] = field(default_factory=dict)
    task_index: Optional[Dict[str, Dict[str, List[TaskInfo]]]] = None
    excluded_names: frozenset = frozenset()
    _instances_memo: Dict[str, List[TaskInfo]] = field(
        default_factory=dict, init=False, repr=False, compare=False
    )
    _counts_memo: Dict[tuple, Dict[str, int]] = field(
        default_factory=dict, init=False, repr=False, compare=False
    )
    _values_memo: Dict[str, set] = field(
        default_factory=dict, init=False, repr=False, compare=False
    )
    _recorded: List[TaskInfo] = field(
        default_factory=list, init=False, repr=False, compare=False
    )

    def record_tasks(self, infos: List[TaskInfo]) -> None:
        """Append just-placed tasks so max-per/group-by rules count
        them for subsequent instances of the same requirement."""
        if self.task_index is not None:
            self._recorded.extend(infos)
        else:
            self.existing_tasks.extend(infos)
        self._instances_memo.clear()
        self._counts_memo.clear()

    def host_field(self, host: TpuHost, field_name: str) -> str:
        return host_field(host, field_name)

    def field_values(self, field_name: str) -> set:
        """Every distinct value of ``field_name`` across the fleet."""
        values = self._values_memo.get(field_name)
        if values is None:
            values = {
                host_field(h, field_name) for h in self.hosts.values()
            }
            self._values_memo[field_name] = values
        return values

    def tasks_of_pod(self, pod_type: str) -> List[TaskInfo]:
        # one counted entry per pod instance (not per task)
        cached = self._instances_memo.get(pod_type)
        if cached is None:
            seen = {}
            if self.task_index is not None:
                for key, infos in self.task_index.get(pod_type, {}).items():
                    for info in infos:
                        if info.name not in self.excluded_names:
                            # sibling tasks of one instance share the
                            # host, so any non-excluded one represents
                            # the instance for placement purposes
                            seen[key] = info
                            break
                extra = self._recorded
            else:
                extra = self.existing_tasks
            # recorded (just-placed) tasks are NEVER excluded: they
            # carry this requirement's own names, but an earlier
            # instance of a multi-instance requirement must count for
            # max-per/group-by on the later ones (the legacy path
            # appends them unfiltered for the same reason)
            for info in extra:
                if info.pod_type == pod_type:
                    seen[f"{info.pod_type}-{info.pod_index}"] = info
            cached = list(seen.values())
            self._instances_memo[pod_type] = cached
        return cached

    def counts_for(self, field_name: str, pod_type: str) -> Dict[str, int]:
        """Instance count per distinct field value (memoized) — the
        shared basis of count_on and index pre-filtering, so a rule's
        filter() and its candidate set can never disagree."""
        key = (field_name, pod_type)
        counts = self._counts_memo.get(key)
        if counts is None:
            counts = {}
            for info in self.tasks_of_pod(pod_type):
                host = self.hosts.get(info.agent_id)
                if host is not None:
                    actual = host_field(host, field_name)
                    counts[actual] = counts.get(actual, 0) + 1
            self._counts_memo[key] = counts
        return counts

    def count_on(self, field_name: str, value: str, pod_type: str) -> int:
        return self.counts_for(field_name, pod_type).get(value, 0)


class PlacementRule:
    def filter(
        self, snapshot: ResourceSnapshot, ctx: PlacementContext
    ) -> EvaluationOutcome:
        raise NotImplementedError

    def candidate_host_ids(
        self, ctx: PlacementContext, index: HostIndex
    ) -> Optional[set]:
        """Indexed pre-filtering: the host ids this rule could pass,
        or None when the rule cannot bound its candidates (the
        evaluator then scans).  MUST be a superset of the hosts
        ``filter`` would pass — filter() still runs on every
        candidate, so over-approximation costs time, never
        correctness; UNDER-approximation changes placement."""
        return None

    def candidate_key(self):
        """Hashable identity of this rule's candidate set when it is
        STATIC — a pure function of fleet topology, independent of
        the placement context's task counts — or None when dynamic
        (max-per / group-by / round-robin consult live counts and
        must recompute).  Static sets are memoized per topology
        generation (HostIndex.rule_candidates), so a deploy of N
        instances pays ONE candidate-set computation instead of N
        fleet-sized set algebras (the PR 9 remainder)."""
        return None


class PassthroughRule(PlacementRule):
    def filter(self, snapshot, ctx):
        return EvaluationOutcome.ok("passthrough")


class AndRule(PlacementRule):
    def __init__(self, rules: Sequence[PlacementRule]):
        self.rules = list(rules)

    def filter(self, snapshot, ctx):
        children = [r.filter(snapshot, ctx) for r in self.rules]
        passed = all(c.passed for c in children)
        outcome = EvaluationOutcome(
            passed, "and", "all passed" if passed else "a sub-rule failed"
        )
        outcome.children = children
        return outcome

    def candidate_host_ids(self, ctx, index):
        # intersection of every bounding child; an unbounded child
        # (None) constrains nothing
        out = None
        for rule in self.rules:
            cand = rule.candidate_host_ids(ctx, index)
            if cand is None:
                continue
            out = set(cand) if out is None else out & cand
            if not out:
                return out
        return out

    def candidate_key(self):
        # static only when EVERY bounding child is static: a dynamic
        # child changes the intersection between instances
        keys = []
        for rule in self.rules:
            key = rule.candidate_key()
            if key is None and rule.candidate_host_ids.__func__ is not \
                    PlacementRule.candidate_host_ids:
                return None
            keys.append(key)
        return ("and", tuple(keys))


class OrRule(PlacementRule):
    def __init__(self, rules: Sequence[PlacementRule]):
        self.rules = list(rules)

    def filter(self, snapshot, ctx):
        children = [r.filter(snapshot, ctx) for r in self.rules]
        passed = any(c.passed for c in children)
        outcome = EvaluationOutcome(
            passed, "or", "a sub-rule passed" if passed else "no sub-rule passed"
        )
        outcome.children = children
        return outcome

    def candidate_host_ids(self, ctx, index):
        # union; ANY unbounded branch makes the whole rule unbounded
        out: set = set()
        for rule in self.rules:
            cand = rule.candidate_host_ids(ctx, index)
            if cand is None:
                return None
            out |= cand
        return out

    def candidate_key(self):
        keys = []
        for rule in self.rules:
            key = rule.candidate_key()
            if key is None and rule.candidate_host_ids.__func__ is not \
                    PlacementRule.candidate_host_ids:
                return None  # a dynamic bounding child: recompute
            keys.append(key)
        return ("or", tuple(keys))


class NotRule(PlacementRule):
    def __init__(self, rule: PlacementRule):
        self.rule = rule

    def filter(self, snapshot, ctx):
        child = self.rule.filter(snapshot, ctx)
        outcome = EvaluationOutcome(
            not child.passed, "not", f"inverted {child.source}"
        )
        outcome.children = [child]
        return outcome


class FieldMatchRule(PlacementRule):
    """hostname/zone/region/attribute exact or regex match.

    Reference: HostnameRule/ZoneRule/RegionRule/AttributeRule +
    ExactMatcher/RegexMatcher.
    """

    def __init__(self, field_name: str, values: List[str], regex: bool = False,
                 invert: bool = False):
        self.field_name = field_name
        self.values = values
        self.regex = regex
        self.invert = invert

    def filter(self, snapshot, ctx):
        actual = ctx.host_field(snapshot.host, self.field_name)
        if self.regex:
            matched = any(re.fullmatch(v, actual) for v in self.values)
        else:
            matched = actual in self.values
        ok = matched != self.invert
        name = f"{'un' if self.invert else ''}match:{self.field_name}"
        if ok:
            return EvaluationOutcome.ok(name, f"{actual!r} ok")
        return EvaluationOutcome.fail(
            name,
            f"host {snapshot.host.host_id} {self.field_name}={actual!r} "
            f"{'matches' if self.invert else 'not in'} {self.values}",
        )

    def candidate_host_ids(self, ctx, index):
        value_index = index.value_index(self.field_name)
        if self.regex:
            matched: set = set()
            # distinct values are few; the regex runs per value, not
            # per host
            for value, hosts in value_index.items():
                if any(re.fullmatch(v, value) for v in self.values):
                    matched |= hosts
        else:
            matched = set()
            for v in self.values:
                matched |= value_index.get(v, frozenset())
        if self.invert:
            return index.universe() - matched
        return matched

    def candidate_key(self):
        # pure function of host fields: the candidate set (including
        # the O(fleet) inverted-match universe subtraction) only moves
        # when topology does
        return (
            "match", self.field_name, tuple(self.values),
            self.regex, self.invert,
        )


class MaxPerRule(PlacementRule):
    """At most N instances of this pod per distinct field value.

    Reference: MaxPerHostnameRule / MaxPerZoneRule / etc.
    """

    def __init__(self, field_name: str, max_count: int):
        self.field_name = field_name
        self.max_count = max_count

    def filter(self, snapshot, ctx):
        value = ctx.host_field(snapshot.host, self.field_name)
        count = ctx.count_on(self.field_name, value, ctx.pod_type)
        if count < self.max_count:
            return EvaluationOutcome.ok(
                f"max-per-{self.field_name}",
                f"{count}/{self.max_count} on {value!r}",
            )
        return EvaluationOutcome.fail(
            f"max-per-{self.field_name}",
            f"already {count}/{self.max_count} instances of "
            f"{ctx.pod_type!r} on {self.field_name}={value!r}",
        )

    def candidate_host_ids(self, ctx, index):
        # exclude hosts whose field value already carries max_count
        # instances — the same counts filter() consults
        counts = ctx.counts_for(self.field_name, ctx.pod_type)
        saturated = [
            v for v, n in counts.items() if n >= self.max_count
        ]
        if not saturated:
            return index.universe()
        value_index = index.value_index(self.field_name)
        out = set(index.universe())
        for v in saturated:
            out -= value_index.get(v, frozenset())
        return out


class GroupByRule(PlacementRule):
    """Spread instances evenly across field values.

    Reference: RoundRobinByHostname/Zone + marathon GROUP_BY.
    ``expected_values`` bounds the divisor when known (GROUP_BY:n).
    """

    def __init__(self, field_name: str, expected_values: int = 0):
        self.field_name = field_name
        self.expected_values = expected_values

    def filter(self, snapshot, ctx):
        value = ctx.host_field(snapshot.host, self.field_name)
        values = ctx.field_values(self.field_name) | {value}
        divisor = self.expected_values or len(values) or 1
        total = len(ctx.tasks_of_pod(ctx.pod_type)) + 1  # incl. this one
        ceiling = math.ceil(total / divisor)
        count = ctx.count_on(self.field_name, value, ctx.pod_type)
        if count < ceiling:
            return EvaluationOutcome.ok(
                f"group-by-{self.field_name}",
                f"{count}<{ceiling} on {value!r}",
            )
        return EvaluationOutcome.fail(
            f"group-by-{self.field_name}",
            f"{self.field_name}={value!r} already has {count} "
            f"(ceiling {ceiling}) of {ctx.pod_type!r}",
        )

    def candidate_host_ids(self, ctx, index):
        # same ceiling arithmetic as filter(): an up host's value is
        # already in the fleet value set, so the divisor is constant
        # across candidates
        values = ctx.field_values(self.field_name)
        divisor = self.expected_values or len(values) or 1
        total = len(ctx.tasks_of_pod(ctx.pod_type)) + 1
        ceiling = math.ceil(total / divisor)
        counts = ctx.counts_for(self.field_name, ctx.pod_type)
        saturated = [v for v, n in counts.items() if n >= ceiling]
        if not saturated:
            return index.universe()
        value_index = index.value_index(self.field_name)
        out = set(index.universe())
        for v in saturated:
            out -= value_index.get(v, frozenset())
        return out


class TaskTypeRule(PlacementRule):
    """Colocate with / avoid hosts running another pod type.

    Reference: TaskTypeRule.colocateWith / avoid.
    """

    def __init__(self, other_pod_type: str, colocate: bool):
        self.other = other_pod_type
        self.colocate = colocate

    def filter(self, snapshot, ctx):
        hosts_of_other = {
            info.agent_id for info in ctx.tasks_of_pod(self.other)
        }
        on_host = snapshot.host.host_id in hosts_of_other
        name = f"task-type-{'colocate' if self.colocate else 'avoid'}:{self.other}"
        if self.colocate:
            if not hosts_of_other:
                # nothing to colocate with yet: allow anywhere (the
                # reference behaves the same when the target is absent)
                return EvaluationOutcome.ok(name, f"no {self.other!r} tasks yet")
            if on_host:
                return EvaluationOutcome.ok(name, "colocated")
            return EvaluationOutcome.fail(
                name, f"host has no {self.other!r} task"
            )
        if on_host:
            return EvaluationOutcome.fail(
                name, f"host already runs {self.other!r}"
            )
        return EvaluationOutcome.ok(name, "avoided")

    def candidate_host_ids(self, ctx, index):
        hosts_of_other = {
            info.agent_id for info in ctx.tasks_of_pod(self.other)
        }
        if self.colocate:
            if not hosts_of_other:
                return index.universe()  # nothing to colocate with yet
            return hosts_of_other & index.universe()
        return index.universe() - hosts_of_other


class AgentRule(PlacementRule):
    """Pin to / avoid specific host ids.

    Reference: AgentRule (agent-id targeted placement).  The avoid form
    is the TPU maintenance-drain verb: ``agent:avoid:h3`` keeps new
    placements off a host scheduled for maintenance while existing
    tasks drain.
    """

    def __init__(self, host_ids: List[str], avoid: bool = False):
        self.host_ids = set(host_ids)
        self.avoid = avoid

    def filter(self, snapshot, ctx):
        on_list = snapshot.host.host_id in self.host_ids
        name = f"agent-{'avoid' if self.avoid else 'match'}"
        if on_list != self.avoid:
            return EvaluationOutcome.ok(name, snapshot.host.host_id)
        return EvaluationOutcome.fail(
            name,
            f"host {snapshot.host.host_id!r} "
            f"{'is drained' if self.avoid else 'not in'} "
            f"{sorted(self.host_ids)}",
        )

    def candidate_host_ids(self, ctx, index):
        if self.avoid:
            return index.universe() - self.host_ids
        return self.host_ids & index.universe()


class RoundRobinByRule(PlacementRule):
    """Strict round robin: a host's field value may hold a new instance
    only while no other known value holds fewer.

    Reference: RoundRobinByHostname/Attribute/Region/ZoneRule — unlike
    GROUP_BY's ceiling (which allows transient imbalance while filling)
    round robin never lets any value get 2 ahead of the emptiest.
    ``expected_values`` bounds the value set when topology knowledge is
    partial (reference: the optional value-count parameter).
    """

    def __init__(self, field_name: str, expected_values: int = 0):
        self.field_name = field_name
        self.expected_values = expected_values

    def filter(self, snapshot, ctx):
        value = ctx.host_field(snapshot.host, self.field_name)
        values = ctx.field_values(self.field_name) | {value}
        counts = {
            v: ctx.count_on(self.field_name, v, ctx.pod_type) for v in values
        }
        floor = min(counts.values())
        if self.expected_values and len(values) < self.expected_values:
            # declared values not yet visible in the topology are empty
            # by definition (reference: RoundRobin treats unknown
            # declared values as the floor)
            floor = 0
        name = f"round-robin-{self.field_name}"
        if counts[value] <= floor:
            return EvaluationOutcome.ok(
                name, f"{value!r} at {counts[value]} (floor {floor})"
            )
        return EvaluationOutcome.fail(
            name,
            f"{self.field_name}={value!r} has {counts[value]} of "
            f"{ctx.pod_type!r}, another value is at {floor}",
        )

    def candidate_host_ids(self, ctx, index):
        # the floor is computed over the FLEET value set (incl. values
        # seen only on down hosts — count 0), exactly as filter() does
        values = ctx.field_values(self.field_name)
        task_counts = ctx.counts_for(self.field_name, ctx.pod_type)
        counts = {v: task_counts.get(v, 0) for v in values}
        floor = min(counts.values()) if counts else 0
        if self.expected_values and len(values) < self.expected_values:
            floor = 0
        value_index = index.value_index(self.field_name)
        out: set = set()
        for v, n in counts.items():
            if n <= floor:
                out |= value_index.get(v, frozenset())
        return out


class VolumeProfilesRule(PlacementRule):
    """The pod's volumes demand storage profiles (reference: profile
    MOUNT volumes matched against DC/OS storage profiles,
    VolumeEvaluationStage.java): the host must advertise every
    requested profile in its ``volume_profiles`` attribute
    (comma-separated, e.g. ``volume_profiles: "ssd,nvme"``)."""

    def __init__(self, profiles):
        self.profiles = sorted(set(profiles))

    def filter(self, snapshot, ctx):
        advertised = {
            p.strip()
            for p in snapshot.host.attributes.get(
                "volume_profiles", ""
            ).split(",")
            if p.strip()
        }
        missing = [p for p in self.profiles if p not in advertised]
        if not missing:
            return EvaluationOutcome.ok(
                "volume-profiles", ",".join(self.profiles) or "any"
            )
        return EvaluationOutcome.fail(
            "volume-profiles",
            f"host {snapshot.host.host_id} lacks storage profile(s) "
            f"{missing} (advertises {sorted(advertised) or 'none'})",
        )

    def candidate_host_ids(self, ctx, index):
        # the attribute is a free-form comma list: parse each DISTINCT
        # advertised string once (few) instead of per host
        out: set = set()
        for raw, hosts in index.value_index("volume_profiles").items():
            advertised = {p.strip() for p in raw.split(",") if p.strip()}
            if all(p in advertised for p in self.profiles):
                out |= hosts
        return out


class SameSliceRule(PlacementRule):
    """TPU-first: all instances of the pod on one physical slice."""

    def filter(self, snapshot, ctx):
        slices = {
            ctx.hosts[i.agent_id].slice_id
            for i in ctx.tasks_of_pod(ctx.pod_type)
            if i.agent_id in ctx.hosts
        }
        if not slices or snapshot.host.slice_id in slices:
            return EvaluationOutcome.ok("same-slice", snapshot.host.slice_id)
        return EvaluationOutcome.fail(
            "same-slice",
            f"pod pinned to slice {sorted(slices)}, host is on "
            f"{snapshot.host.slice_id!r}",
        )

    def candidate_host_ids(self, ctx, index):
        slices = {
            ctx.hosts[i.agent_id].slice_id
            for i in ctx.tasks_of_pod(ctx.pod_type)
            if i.agent_id in ctx.hosts
        }
        if not slices:
            return index.universe()
        value_index = index.value_index("slice")
        out: set = set()
        for s in slices:
            out |= value_index.get(s, frozenset())
        return out


# ---------------------------------------------------------------------------
# Parsers
# ---------------------------------------------------------------------------


def parse_placement(text: str) -> PlacementRule:
    """Parse the YAML ``placement:`` field.

    Two dialects, as in the reference: the marathon-style JSON list
    (MarathonConstraintParser.java) and a colon DSL.  Colon DSL:

        max-per-host:1
        max-per-zone:2
        hostname:exact:h1,h2        hostname:regex:tpu-.*
        zone:exact:us-central2-b    attribute:tier:premium
        task-type:avoid:data        task-type:colocate:data
        group-by:zone               round-robin:zone[:n]
        agent:exact:h1,h2           agent:avoid:h3   (maintenance drain)
        generation:v5e              same-slice
        rule1 && rule2              (conjunction)
        rule1 || rule2              (disjunction; binds looser than &&)
    """
    text = (text or "").strip()
    if not text:
        return PassthroughRule()
    if text.startswith("["):
        return _parse_marathon(text)
    alternatives = [a.strip() for a in text.split("||") if a.strip()]
    or_rules: List[PlacementRule] = []
    for alternative in alternatives:
        parts = [p.strip() for p in alternative.split("&&") if p.strip()]
        rules = [_parse_one(p) for p in parts]
        or_rules.append(rules[0] if len(rules) == 1 else AndRule(rules))
    return or_rules[0] if len(or_rules) == 1 else OrRule(or_rules)


_FIELD_ALIASES = {"host": "hostname", "hostname": "hostname", "zone": "zone",
                  "region": "region", "slice": "slice"}


def _parse_one(text: str) -> PlacementRule:
    try:
        return _parse_one_inner(text)
    except (IndexError, KeyError) as e:
        # arity errors surface as parse errors, not crashes — the spec
        # validator turns these into config errors
        raise ValueError(f"malformed placement rule {text!r}: {e}")


def _parse_one_inner(text: str) -> PlacementRule:
    parts = text.split(":")
    head = parts[0].lower()
    if head == "max-per-host":
        return MaxPerRule("hostname", int(parts[1]))
    if head in ("max-per-zone", "max-per-region", "max-per-slice"):
        return MaxPerRule(head.split("-")[-1], int(parts[1]))
    if head == "max-per-attribute":
        return MaxPerRule(parts[1], int(parts[2]))
    if head == "group-by":
        expected = int(parts[2]) if len(parts) > 2 else 0
        return GroupByRule(_FIELD_ALIASES.get(parts[1], parts[1]), expected)
    if head in _FIELD_ALIASES and len(parts) >= 3:
        field_name = _FIELD_ALIASES[head]
        mode, values = parts[1].lower(), parts[2]
        return FieldMatchRule(
            field_name, values.split(","), regex=(mode == "regex")
        )
    if head == "attribute" and len(parts) >= 3:
        return FieldMatchRule(parts[1], [":".join(parts[2:])])
    if head == "generation" and len(parts) == 2:
        return FieldMatchRule("generation", [parts[1]])
    if head == "task-type" and len(parts) == 3:
        return TaskTypeRule(parts[2], colocate=(parts[1].lower() == "colocate"))
    if head == "round-robin" and len(parts) >= 2:
        expected = int(parts[2]) if len(parts) > 2 else 0
        return RoundRobinByRule(
            _FIELD_ALIASES.get(parts[1], parts[1]), expected
        )
    if head == "agent" and len(parts) >= 3:
        mode = parts[1].lower()
        ids = parts[2].split(",")
        if mode in ("exact", "match"):
            return AgentRule(ids)
        if mode == "avoid":
            return AgentRule(ids, avoid=True)
    if head == "same-slice":
        return SameSliceRule()
    raise ValueError(f"unknown placement rule: {text!r}")


def _parse_marathon(text: str) -> PlacementRule:
    """Reference: MarathonConstraintParser.java — JSON like
    [["hostname","UNIQUE"], ["zone","GROUP_BY","3"], ["tier","IS","hot"],
    ["hostname","CLUSTER","h1"], ["zone","LIKE","us-.*"], ["zone","UNLIKE",".."],
    ["hostname","MAX_PER","2"]]."""
    try:
        constraints = json.loads(text)
    except json.JSONDecodeError as e:
        raise ValueError(f"bad marathon placement JSON: {e}") from e
    if constraints and isinstance(constraints[0], str):
        constraints = [constraints]  # single constraint shorthand
    rules: List[PlacementRule] = []
    for constraint in constraints:
        if not isinstance(constraint, list) or len(constraint) < 2:
            raise ValueError(f"bad marathon constraint: {constraint!r}")
        raw_field, op = constraint[0], constraint[1].upper()
        field_name = _FIELD_ALIASES.get(raw_field, raw_field)
        arg = constraint[2] if len(constraint) > 2 else None
        if op == "UNIQUE":
            rules.append(MaxPerRule(field_name, 1))
        elif op == "MAX_PER":
            rules.append(MaxPerRule(field_name, int(arg)))
        elif op == "GROUP_BY":
            rules.append(GroupByRule(field_name, int(arg) if arg else 0))
        elif op == "IS" or op == "CLUSTER":
            if arg is None:
                raise ValueError(f"{op} requires a value: {constraint!r}")
            rules.append(FieldMatchRule(field_name, [str(arg)]))
        elif op == "LIKE":
            rules.append(FieldMatchRule(field_name, [str(arg)], regex=True))
        elif op == "UNLIKE":
            rules.append(
                FieldMatchRule(field_name, [str(arg)], regex=True, invert=True)
            )
        else:
            raise ValueError(f"unknown marathon operator {op!r}")
    return rules[0] if len(rules) == 1 else AndRule(rules)
