"""PlanGenerator: the YAML ``plans:`` section -> Plan objects.

Reference: specification/PlanGenerator.java + yaml RawPlan/RawPhase
(specification/yaml/RawServiceSpec beans).  YAML shape:

    plans:
      deploy:
        strategy: serial
        phases:
          first-phase:
            strategy: parallel
            pod: hello
            steps:            # optional explicit per-instance steps
              - 0: [[task-a, task-b]]
              - 1: [[task-a]]

Without ``steps`` a phase covers every instance of the pod with every
task (gang pods: one step for the whole slice).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from dcos_commons_tpu.plan.backoff import Backoff
from dcos_commons_tpu.plan.builders import DeployPlanFactory
from dcos_commons_tpu.plan.phase import Phase
from dcos_commons_tpu.plan.plan import Plan
from dcos_commons_tpu.plan.step import DeploymentStep, PodInstanceRequirement
from dcos_commons_tpu.plan.strategy import strategy_for_name
from dcos_commons_tpu.specification.specs import ServiceSpec, SpecError, task_full_name
from dcos_commons_tpu.state.state_store import StateStore


class PlanGenerator:
    def __init__(self, backoff: Optional[Backoff] = None):
        self._factory = DeployPlanFactory(backoff)
        self._backoff = backoff

    def generate(
        self,
        spec: ServiceSpec,
        plan_name: str,
        raw_plan: Dict[str, Any],
        state_store: StateStore,
        target_config_id: str,
    ) -> Plan:
        phases: List[Phase] = []
        for phase_name, raw_phase in (raw_plan.get("phases") or {}).items():
            phases.append(
                self._generate_phase(
                    spec, phase_name, raw_phase or {}, state_store, target_config_id
                )
            )
        return Plan(
            plan_name,
            phases,
            strategy_for_name(str(raw_plan.get("strategy", "serial"))),
        )

    def _generate_phase(
        self,
        spec: ServiceSpec,
        phase_name: str,
        raw_phase: Dict[str, Any],
        state_store: StateStore,
        target_config_id: str,
    ) -> Phase:
        pod_name = raw_phase.get("pod")
        if not pod_name:
            raise SpecError(f"phase {phase_name!r} requires a pod")
        pod = spec.pod(str(pod_name))
        strategy_name = str(raw_phase.get("strategy", "serial"))
        raw_steps = raw_phase.get("steps")
        if not raw_steps:
            return self._factory.build_phase(
                pod, state_store, target_config_id, strategy_name,
                phase_name=phase_name,
            )
        steps: List[DeploymentStep] = []
        for entry in raw_steps:
            if not isinstance(entry, dict) or len(entry) != 1:
                raise SpecError(
                    f"phase {phase_name!r}: each step must be one "
                    "{index: [[tasks...]]} mapping"
                )
            ((raw_index, task_groups),) = entry.items()
            try:
                index = int(raw_index)
            except (TypeError, ValueError):
                raise SpecError(
                    f"phase {phase_name!r}: step index {raw_index!r} "
                    "is not an integer"
                )
            if not 0 <= index < pod.count:
                raise SpecError(
                    f"phase {phase_name!r}: step index {index} out of "
                    f"range for pod {pod.type!r} (count {pod.count})"
                )
            for tasks in task_groups:
                task_list = [str(t) for t in tasks]
                unknown = [
                    t for t in task_list
                    if t not in {s.name for s in pod.tasks}
                ]
                if unknown:
                    raise SpecError(
                        f"phase {phase_name!r}: unknown tasks {unknown} "
                        f"for pod {pod.type!r}"
                    )
                requirement = PodInstanceRequirement(
                    pod=pod, instances=[index], tasks_to_launch=task_list
                )
                step = DeploymentStep(
                    f"{pod.type}-{index}:[{','.join(task_list)}]",
                    requirement,
                    backoff=self._backoff,
                )
                self._factory.seed_step_from_state(
                    step, pod, [index], state_store, target_config_id
                )
                steps.append(step)
        return Phase(phase_name, steps, strategy_for_name(strategy_name))
