"""Persistent XLA compilation cache plumbing.

Everything under jit is traced once and compiled; on a fresh process
that compile dominates small-workload wall-clock (round-2 bench: 16s
of the 23.6s MNIST deploy was XLA compilation).  The persistent cache
keys compiled executables by HLO + platform, so any repeat deploy —
scheduler restart, recovery relaunch, warm bench pass — skips straight
to execution.  The reference has no analogue (its tasks are arbitrary
binaries); this is TPU-first operational surface.
"""

from __future__ import annotations

import os

CACHE_ENV = "JAX_COMPILATION_CACHE_DIR"


def enable_compilation_cache(cache_dir: str = "") -> bool:
    """Point JAX's persistent compilation cache at ``cache_dir`` (or
    $JAX_COMPILATION_CACHE_DIR).  Returns True when enabled.  Safe to
    call before or after first device use; no-op without a directory.

    The min-compile-time floor is zeroed: a scheduler deploy launches
    MANY short-compile programs (MLP train step, eval, host transfers)
    and the default 1s floor would skip exactly the programs a warm
    relaunch needs."""
    cache_dir = cache_dir or os.environ.get(CACHE_ENV, "")
    if not cache_dir:
        return False
    import jax

    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    return True
