"""racecheck: thread-ownership static analysis + happens-before races.

Per-rule fixtures for the static half (violation caught, suppression
honored, and the legal idioms — common lock, queue handoff, *_locked
convention, `# racecheck: handoff=` annotation — stay quiet), plus
the dynamic vector-clock checker: a seeded two-thread race is flagged
with both stacks, every ordering edge (lock, queue, start/join,
Event, Condition) suppresses the pair, and the PR 16 bug class
(foreign-thread splice installing a row mid-decode-tick) has a
dedicated regression: the pre-fix shape races, the real PagedEngine
protocol runs clean under full instrumentation.

The repo-wide gate (zero findings, empty baseline) lives in
tests/test_lint_gate.py next to the other analyzers' gates.
"""

import os
import queue
import textwrap
import threading
import time

import numpy as np

from dcos_commons_tpu.analysis import lockcheck, racecheck
from dcos_commons_tpu.analysis.racecheck import (
    RULE_CALLBACK,
    RULE_CHECK_THEN_ACT,
    RULE_COLLECTIVE,
    RULE_LOCK_CYCLE,
    RULE_UNGUARDED,
    RULE_UNORDERED,
    race_rule_catalog,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _race_fixture(tmp_path, source, rule_id=None,
                  rel="dcos_commons_tpu/mod.py"):
    """Analyze one fixture file placed at ``rel`` under a fake repo
    root; returns the RaceResult plus (findings, suppressed) filtered
    to ``rule_id``."""
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    result = racecheck.analyze_paths([str(path)], str(tmp_path))
    pick = lambda fs: [f for f in fs if rule_id is None or f.rule == rule_id]  # noqa: E731
    return result, pick(result.findings), pick(result.suppressed)


# -- race-unguarded-shared-write --------------------------------------


_PUMP = """
import threading

class Pump:
    def __init__(self):
        self._lock = threading.Lock()
        self.level = 0
        self._t = None

    def start(self):
        self._t = threading.Thread(target=self._loop, name="pump-loop")
        self._t.start()

    def _loop(self):
        self.level = 1

    def set_level(self, n):
        self.level = n
"""


def test_rule_unguarded_shared_write(tmp_path):
    result, findings, _ = _race_fixture(tmp_path, _PUMP, RULE_UNGUARDED)
    assert len(findings) == 1
    assert "Pump.level" in findings[0].message
    assert "pump-loop" in findings[0].message
    # the flagged attr is in the dynamic probe set
    assert "level" in result.shared_attrs.get("Pump", [])
    # ...and the discovered thread role is surfaced for the trend keys
    assert "pump-loop" in result.roles.get("Pump", [])
    # sdklint suppression on the write line is honored
    suppressed_src = _PUMP.replace(
        "        self.level = 1",
        "        self.level = 1  "
        "# sdklint: disable=race-unguarded-shared-write — fixture",
    )
    result, findings, suppressed = _race_fixture(
        tmp_path, suppressed_src, RULE_UNGUARDED
    )
    assert not findings and len(suppressed) == 1
    # a triaged attr leaves the probe set: the rationale, not a lock,
    # orders those writes — the dynamic checker must not re-flag it
    assert "level" not in result.shared_attrs.get("Pump", [])


def test_rule_unguarded_common_lock_is_clean(tmp_path):
    guarded = _PUMP.replace(
        "        self.level = 1",
        "        with self._lock:\n            self.level = 1",
    ).replace(
        "        self.level = n",
        "        with self._lock:\n            self.level = n",
    )
    result, findings, _ = _race_fixture(tmp_path, guarded, RULE_UNGUARDED)
    assert not findings
    # guarded sharing stays in the probe set (the dynamic half checks
    # the lock is actually sufficient at runtime)
    assert "level" in result.shared_attrs.get("Pump", [])


def test_rule_unguarded_handoff_annotation_exempts(tmp_path):
    annotated = _PUMP.replace(
        "        self.level = 1",
        "        # racecheck: handoff=monotonic flip, readers tolerate"
        " either value\n        self.level = 1",
    )
    result, findings, suppressed = _race_fixture(
        tmp_path, annotated, RULE_UNGUARDED
    )
    assert not findings and len(suppressed) == 1
    assert "level" not in result.shared_attrs.get("Pump", [])


def test_rule_unguarded_queue_handoff_is_clean(tmp_path):
    src = """
    import queue
    import threading

    class Mailbox:
        def __init__(self):
            self._inbox = queue.Queue()

        def start(self):
            t = threading.Thread(target=self._loop, name="mail-loop")
            t.start()

        def post(self, msg):
            self._inbox.put(msg)

        def _loop(self):
            while True:
                self._inbox.get()
    """
    _, findings, _ = _race_fixture(tmp_path, src, RULE_UNGUARDED)
    assert not findings


def test_rule_unguarded_locked_convention_is_clean(tmp_path):
    src = """
    import threading

    class Board:
        def __init__(self):
            self._lock = threading.Lock()
            self._view = ()
            self._cells = {}

        def start(self):
            t = threading.Thread(target=self._loop, name="board-loop")
            t.start()

        def _loop(self):
            with self._lock:
                self._cells["tick"] = 1
                self._rebuild_locked()

        def put(self, k, v):
            with self._lock:
                self._cells[k] = v
                self._rebuild_locked()

        def _rebuild_locked(self):
            self._view = tuple(self._cells)

        def view(self):
            return self._view
    """
    result, findings, _ = _race_fixture(tmp_path, src, RULE_UNGUARDED)
    assert not findings
    # both shared attrs probe-eligible; the snapshot read needs no lock
    assert set(result.shared_attrs.get("Board", [])) == {
        "_cells", "_view",
    }


# -- race-callback-thread ---------------------------------------------


_METER = """
import threading

class Meter:
    def __init__(self):
        self._events = []
        self._t = None

    def start(self, registry):
        self._t = threading.Thread(target=self._loop, name="meter-loop")
        self._t.start()
        registry.subscribe(lambda e: self._events.append(e))

    def _loop(self):
        pass
"""


def test_rule_callback_thread(tmp_path):
    _, findings, _ = _race_fixture(tmp_path, _METER, RULE_CALLBACK)
    assert len(findings) == 1
    assert "self._events" in findings[0].message
    suppressed_src = _METER.replace(
        "        registry.subscribe(lambda e: self._events.append(e))",
        "        registry.subscribe(lambda e: self._events.append(e))  "
        "# sdklint: disable=race-callback-thread — registry is "
        "single-threaded",
    )
    _, findings, suppressed = _race_fixture(
        tmp_path, suppressed_src, RULE_CALLBACK
    )
    assert not findings and len(suppressed) == 1


# -- race-collective-offloop ------------------------------------------


_TRAINER = """
import threading
from jax import lax

class Trainer:
    def start(self):
        t = threading.Thread(target=self._loop, name="train-loop")
        t.start()

    def _loop(self):
        lax.psum(1, "dp")
"""


def test_rule_collective_offloop(tmp_path):
    _, findings, _ = _race_fixture(tmp_path, _TRAINER, RULE_COLLECTIVE)
    assert len(findings) == 1
    assert "psum" in findings[0].message
    assert "train-loop" in findings[0].message
    suppressed_src = _TRAINER.replace(
        '        lax.psum(1, "dp")',
        '        lax.psum(1, "dp")  '
        "# sdklint: disable=race-collective-offloop — single-host tool",
    )
    _, findings, suppressed = _race_fixture(
        tmp_path, suppressed_src, RULE_COLLECTIVE
    )
    assert not findings and len(suppressed) == 1


# -- race-check-then-act ----------------------------------------------


_LEDGER = """
import threading

class Ledger:
    def __init__(self):
        self._lock = threading.Lock()
        self._balance = 100

    def start(self):
        t = threading.Thread(target=self._drain, name="ledger-drain")
        t.start()

    def _drain(self):
        with self._lock:
            balance = self._balance
        fee = balance // 10
        with self._lock:
            self._balance = balance - fee
"""


def test_rule_check_then_act(tmp_path):
    _, findings, _ = _race_fixture(tmp_path, _LEDGER, RULE_CHECK_THEN_ACT)
    assert len(findings) == 1
    assert "`balance`" in findings[0].message
    assert "_balance" in findings[0].message
    # merging the critical sections is the fix — and is clean
    merged = """
    import threading

    class Ledger:
        def __init__(self):
            self._lock = threading.Lock()
            self._balance = 100

        def start(self):
            t = threading.Thread(target=self._drain, name="ledger-drain")
            t.start()

        def _drain(self):
            with self._lock:
                balance = self._balance
                self._balance = balance - balance // 10
    """
    _, findings, _ = _race_fixture(tmp_path, merged, RULE_CHECK_THEN_ACT)
    assert not findings
    suppressed_src = _LEDGER.replace(
        "            self._balance = balance - fee",
        "            self._balance = balance - fee  "
        "# sdklint: disable=race-check-then-act — drain is the only "
        "writer",
    )
    _, findings, suppressed = _race_fixture(
        tmp_path, suppressed_src, RULE_CHECK_THEN_ACT
    )
    assert not findings and len(suppressed) == 1


# -- no false positives on the legal idioms together ------------------


def test_clean_threaded_module_has_zero_findings(tmp_path):
    """A realistic server using every legal idiom at once — queue
    handoff in, common-lock stats, *_locked snapshot rebuild, lock-free
    snapshot reads — produces not one finding."""
    src = """
    import queue
    import threading

    class Server:
        def __init__(self):
            self._lock = threading.Lock()
            self._q = queue.Queue()
            self._stats = {}
            self._snapshot = ()

        def start(self):
            t = threading.Thread(target=self._loop, name="server-loop")
            t.start()

        def submit(self, item):
            self._q.put(item)
            with self._lock:
                self._stats["submitted"] = 1
                self._publish_locked()

        def _loop(self):
            while True:
                item = self._q.get()
                with self._lock:
                    self._stats["served"] = item
                    self._publish_locked()

        def _publish_locked(self):
            self._snapshot = tuple(self._stats)

        def peek(self):
            return self._snapshot
    """
    result, findings, _ = _race_fixture(tmp_path, src)
    assert not findings, [f.render() for f in findings]
    assert set(result.shared_attrs.get("Server", [])) == {
        "_snapshot", "_stats",
    }
    assert "server-loop" in result.roles.get("Server", [])


def test_race_rule_catalog_lists_every_rule():
    catalog = race_rule_catalog()
    for rid in (RULE_UNGUARDED, RULE_CALLBACK, RULE_COLLECTIVE,
                RULE_CHECK_THEN_ACT, RULE_LOCK_CYCLE, RULE_UNORDERED):
        assert rid in catalog


def test_env_var_and_lockcheck_alias(monkeypatch):
    """SDKLINT_LOCKCHECK stays a working alias for the unified
    checker: same switch, same report."""
    monkeypatch.delenv("SDKLINT_RACECHECK", raising=False)
    monkeypatch.delenv("SDKLINT_LOCKCHECK", raising=False)
    assert not racecheck.env_requested()
    monkeypatch.setenv("SDKLINT_LOCKCHECK", "1")
    assert racecheck.env_requested()
    assert lockcheck.env_requested()
    monkeypatch.setenv("SDKLINT_RACECHECK", "1")
    monkeypatch.delenv("SDKLINT_LOCKCHECK")
    assert racecheck.env_requested()
    assert lockcheck.ENV_VAR == "SDKLINT_LOCKCHECK"
    assert lockcheck.install is racecheck.install
    assert lockcheck.report is racecheck.report


# -- dynamic half: vector clocks --------------------------------------


def _dyn(case):
    """Run one scenario under instrumentation; returns the report.
    Mirrors the lockcheck_guard idiom: when the session checker is
    active, leave it installed."""
    already = racecheck.is_enabled()
    racecheck.install()
    racecheck.reset()
    try:
        case()
        return racecheck.report()
    finally:
        racecheck.unwatch_types()
        if not already:
            racecheck.uninstall()
        racecheck.reset()


class _Box:
    def __init__(self):
        self.n = 0


def test_dynamic_seeded_two_thread_race_reports_both_stacks():
    box = _Box()

    def case():
        racecheck.watch_type(_Box, ("n",))

        def writer(v):
            box.n = v

        t1 = threading.Thread(target=writer, args=(1,), daemon=True)
        t2 = threading.Thread(target=writer, args=(2,), daemon=True)
        t1.start(); t2.start()
        t1.join(timeout=5); t2.join(timeout=5)

    rep = _dyn(case)
    assert rep.races, rep.describe()
    rec = rep.races[0]
    assert rec.cls == "_Box" and rec.attr == "n"
    assert rec.thread_a != rec.thread_b
    # both writes carry their stacks, pointing back into this test
    assert "test_racecheck" in rec.stack_a
    assert "test_racecheck" in rec.stack_b
    assert RULE_UNORDERED in rep.describe()


def test_dynamic_ordering_edges_suppress_the_pair():
    """The same two-writer shape, ordered four different ways — lock,
    queue handoff, start/join fork, Condition — never races."""
    box = _Box()

    def locked():
        racecheck.watch_type(_Box, ("n",))
        guard = threading.Lock()

        def writer(v):
            with guard:
                box.n = v

        ts = [threading.Thread(target=writer, args=(v,), daemon=True)
              for v in (1, 2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=5)

    def queued():
        racecheck.watch_type(_Box, ("n",))
        q = queue.Queue()

        def producer():
            box.n = 1
            q.put("go")

        def consumer():
            q.get()
            box.n = 2

        t1 = threading.Thread(target=producer, daemon=True)
        t2 = threading.Thread(target=consumer, daemon=True)
        t1.start(); t2.start()
        t1.join(timeout=5); t2.join(timeout=5)

    def forked():
        racecheck.watch_type(_Box, ("n",))
        box.n = 1
        t = threading.Thread(
            target=lambda: setattr(box, "n", 2), daemon=True
        )
        t.start(); t.join(timeout=5)
        box.n = 3

    def notified():
        racecheck.watch_type(_Box, ("n",))
        cv = threading.Condition(threading.Lock())
        ready = []

        def early():
            with cv:
                box.n = 1
                ready.append(True)
                cv.notify()

        def late():
            with cv:
                while not ready:
                    cv.wait(timeout=5)
                box.n = 2

        t2 = threading.Thread(target=late, daemon=True)
        t1 = threading.Thread(target=early, daemon=True)
        t2.start(); t1.start()
        t1.join(timeout=5); t2.join(timeout=5)

    for case in (locked, queued, forked, notified):
        rep = _dyn(case)
        assert not rep.races, (case.__name__, rep.describe())


def test_dynamic_lock_cycle_is_the_race_lock_cycle_rule():
    """PR 2's deadlock detection lives on inside racecheck, reported
    under the race-lock-cycle rule id."""
    def case():
        a = threading.Lock()
        b = threading.Lock()

        def order_ab():
            with a:
                with b:
                    pass

        def order_ba():
            with b:
                with a:
                    pass

        t1 = threading.Thread(target=order_ab, daemon=True)
        t1.start(); t1.join(timeout=5)
        t2 = threading.Thread(target=order_ba, daemon=True)
        t2.start(); t2.join(timeout=5)

    rep = _dyn(case)
    assert len(rep.cycles) == 1, rep.describe()
    assert RULE_LOCK_CYCLE in rep.describe()
    assert not rep.races


# -- the PR 16 regression: foreign-thread splice mid-tick -------------


class _ToyRow:
    def __init__(self):
        self.last_token = 0


def test_pr16_prefix_shape_foreign_splice_races():
    """The bug class PR 16 fixed, reduced to its shape: a decode-tick
    thread samples into a row it picked up OUTSIDE any identity
    snapshot, while a migration thread splice-installs state into the
    same row.  Without the dispatched-row discipline the two writes
    are unordered — the checker flags them with both stacks."""
    row = _ToyRow()

    def case():
        racecheck.watch_type(_ToyRow, ("last_token",))

        def tick_loop():
            for i in range(50):
                row.last_token = i  # pre-fix: no identity check, no cv

        def splice():
            time.sleep(0.001)
            row.last_token = 999  # foreign-thread install mid-tick

        t1 = threading.Thread(target=tick_loop, daemon=True)
        t2 = threading.Thread(target=splice, daemon=True)
        t1.start(); t2.start()
        t1.join(timeout=5); t2.join(timeout=5)

    rep = _dyn(case)
    assert rep.races, rep.describe()
    rec = rep.races[0]
    assert rec.attr == "last_token"
    assert rec.stack_a and rec.stack_b


_P = 4  # page tokens for the toy arena


class _Arena:
    """Content-free device half for the real-engine drive: decode is
    tok+1, prefill stores tokens so page export/import has payload."""

    def __init__(self):
        self.cells = {}
        self.lock = threading.Lock()

    def prefill_chunk(self, padded, slot, table, start, true_len,
                      temp, seed):
        with self.lock:
            for i in range(true_len):
                pos = start + i
                page = int(table[pos // _P])
                self.cells.setdefault(page, {})[pos % _P] = int(
                    padded[0, i]
                )
        return 1

    def decode(self, tok, pos, temps, seeds, tables, n_active):
        time.sleep(0.002)
        return np.asarray(
            [(int(t) + 1) % 50 for t in tok], np.int32
        )

    def read_page(self, page):
        with self.lock:
            return dict(self.cells.get(page, {}))

    def write_page(self, page, payload):
        with self.lock:
            self.cells[page] = dict(payload)


def test_pr16_real_engine_splice_mid_tick_is_ordered():
    """The fixed protocol under full instrumentation: a live
    PagedEngine decodes while migrate_session freezes, streams, and
    cutover-activates the session on a peer from a foreign thread.
    Every engine-state write the static pass calls shared must be
    ordered by the cv — zero unordered pairs, and the migrated
    session still completes."""
    from dcos_commons_tpu.serve.engine import PagedEngine, SlotEngine
    from dcos_commons_tpu.serve.migration import (
        SessionMigratedError,
        migrate_session,
    )

    def make_pod(role):
        arena = _Arena()
        eng = PagedEngine(
            arena.prefill_chunk, arena.decode, 3, 64, 48,
            page_tokens=_P, pages=40, chunk_tokens=8,
            prefix_cache=True, role=role,
            read_page=arena.read_page, write_page=arena.write_page,
            queue_timeout_s=30,
        )
        return eng

    outcome = {}

    def case():
        shared = racecheck.shared_write_map(REPO)
        for cls in (SlotEngine, PagedEngine):
            attrs = shared.get(cls.__name__)
            if attrs:
                racecheck.watch_type(cls, attrs)
        src = make_pod("source")
        dst = make_pod("dest")
        try:
            result = {}

            def client():
                try:
                    result["r"] = src.submit([[3, 1, 4, 1, 5]], 24)
                except BaseException as e:  # noqa: BLE001 — assertion target
                    result["r"] = e

            t = threading.Thread(target=client, daemon=True)
            t.start()
            deadline = time.monotonic() + 10
            rid = None
            while time.monotonic() < deadline:
                sess = src.sessions()
                if sess and sess[0]["state"] == "decode" \
                        and src.stats()["tokens_out"] >= 4:
                    rid = sess[0]["rid"]
                    break
                time.sleep(0.005)
            assert rid is not None, "session never reached mid-decode"
            record = migrate_session(src, dst, rid, dest_name="dst")
            assert record.ok, record
            t.join(timeout=15)
            err = result["r"]
            assert isinstance(err, SessionMigratedError), err
            outcome["out"] = dst.collect(err.dest_rid, timeout=20)
        finally:
            src.stop()
            dst.stop()

    rep = _dyn(case)
    assert not rep.races, rep.describe()
    assert len(outcome["out"]) == 24
