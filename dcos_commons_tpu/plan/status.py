"""Plan element status machine.

Reference: scheduler/plan/Status.java:23-78 — the full vocabulary
including the WAITING (operator interrupt) and DELAYED (launch
backoff) caveats called out in SURVEY.md section 7 hard part 5.
"""

from __future__ import annotations

import enum
from typing import Iterable


class Status(enum.Enum):
    ERROR = "ERROR"            # element has errors (bad spec / failed update)
    WAITING = "WAITING"        # operator interrupted; will not be offered work
    PENDING = "PENDING"        # no work started
    PREPARED = "PREPARED"      # placement evaluated, ops generated
    STARTING = "STARTING"      # tasks launched, awaiting RUNNING
    STARTED = "STARTED"        # tasks RUNNING, awaiting readiness/goal
    COMPLETE = "COMPLETE"      # goal reached
    IN_PROGRESS = "IN_PROGRESS"  # aggregate: some children done, some not
    DELAYED = "DELAYED"        # launch backoff after crash-loop

    @property
    def is_complete(self) -> bool:
        return self is Status.COMPLETE

    @property
    def is_running(self) -> bool:
        """Work actively underway (reference: Status.isRunning)."""
        return self in (
            Status.PREPARED,
            Status.STARTING,
            Status.STARTED,
            Status.IN_PROGRESS,
        )

    @property
    def is_working(self) -> bool:
        """Eligible for or doing work: not terminal, not parked."""
        return self in (
            Status.PENDING,
            Status.PREPARED,
            Status.STARTING,
            Status.STARTED,
            Status.IN_PROGRESS,
            Status.DELAYED,
        )


def aggregate(child_statuses: Iterable[Status], interrupted: bool = False) -> Status:
    """Roll child statuses up to a parent element.

    Reference: the aggregation rules in PlanUtils/Element.getStatus:
    ERROR dominates; all-complete is COMPLETE; an interrupt — the
    parent's own or ANY child's — shows WAITING while incomplete (the
    operator who parked a step must see it in ``plan show``, not a
    parent claiming IN_PROGRESS while nothing can move; plancheck's
    ``interrupt-visible`` invariant found the old child-WAITING-
    behind-IN_PROGRESS/DELAYED masking with a two-event trace);
    untouched is PENDING; otherwise IN_PROGRESS, with DELAYED
    surfaced when nothing else is moving.

    Every clause is an any()/all() over the multiset, so the result
    is permutation-invariant by construction — plancheck's
    ``aggregate-consistent`` invariant and the hypothesis property
    test (tests/test_plan_properties.py) both pin that down.
    """
    statuses = list(child_statuses)
    if not statuses:
        return Status.COMPLETE
    if any(s is Status.ERROR for s in statuses):
        return Status.ERROR
    if all(s is Status.COMPLETE for s in statuses):
        return Status.COMPLETE
    if interrupted or any(s is Status.WAITING for s in statuses):
        return Status.WAITING
    if all(s is Status.PENDING for s in statuses):
        return Status.PENDING
    moving = [s for s in statuses if s.is_running]
    if not moving and any(s is Status.DELAYED for s in statuses):
        return Status.DELAYED
    return Status.IN_PROGRESS
