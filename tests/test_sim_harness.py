"""Sim-harness tests: the reference's ServiceTest.java flows, scripted.

Reference: frameworks/helloworld/src/test/.../ServiceTest.java:43-90
(deploy tick sequence), CustomStepsTest.java (canary proceed),
SchedulerRestartServiceTest.java (resume over one persister).  All
scheduler behavior here is driven through FakeAgent scripting — no
subprocesses, no sleeps.
"""

from dcos_commons_tpu.common import TaskState
from dcos_commons_tpu.offer.inventory import TpuHost
from dcos_commons_tpu.plan.status import Status
from dcos_commons_tpu.testing import (
    AdvanceCycles,
    ExpectDeclined,
    ExpectDeploymentComplete,
    ExpectDistinctHosts,
    ExpectLaunchedTasks,
    ExpectNoLaunches,
    ExpectPlanStatus,
    ExpectRecoveryStep,
    ExpectStepStatus,
    ExpectTaskEnv,
    ExpectTaskKilled,
    ExpectTaskStateStored,
    PlanContinue,
    SendTaskFailed,
    SendTaskRunning,
    ServiceTestRunner,
)

TWO_POD_YAML = """
name: hello-world
pods:
  hello:
    count: 2
    placement: 'max-per-host:1'
    tasks:
      server:
        goal: RUNNING
        cmd: "sleep 1000"
        cpus: 0.1
        memory: 32
"""


def test_deploy_tick_sequence():
    runner = ServiceTestRunner(TWO_POD_YAML)
    runner.run([
        AdvanceCycles(1),
        # serial strategy: only the first instance launches
        ExpectLaunchedTasks("hello-0-server"),
        ExpectStepStatus("deploy", "hello", "hello-0:[server]", Status.STARTING),
        SendTaskRunning("hello-0-server"),
        ExpectStepStatus("deploy", "hello", "hello-0:[server]", Status.COMPLETE),
        AdvanceCycles(1),
        ExpectLaunchedTasks("hello-1-server"),
        SendTaskRunning("hello-1-server"),
        ExpectDeploymentComplete(),
        ExpectDistinctHosts("hello-0-server", "hello-1-server"),
        ExpectTaskEnv("hello-0-server", "POD_INSTANCE_INDEX", "0"),
    ])


def test_insufficient_fleet_declines():
    # max-per-host:1 with a single host: second instance cannot place
    runner = ServiceTestRunner(
        TWO_POD_YAML, hosts=[TpuHost(host_id="only-host")]
    )
    runner.run([
        AdvanceCycles(1),
        ExpectLaunchedTasks("hello-0-server"),
        SendTaskRunning("hello-0-server"),
        AdvanceCycles(2),
        ExpectNoLaunches(),
        ExpectDeclined("hello-[1]"),
        ExpectPlanStatus("deploy", Status.IN_PROGRESS),
    ])
    # capacity arrives (host added) -> deployment finishes
    from dcos_commons_tpu.testing import AddHost

    runner.run([
        AddHost(TpuHost(host_id="late-host")),
        ExpectLaunchedTasks("hello-1-server"),
        SendTaskRunning("hello-1-server"),
        ExpectDeploymentComplete(),
    ])


def test_failure_triggers_recovery():
    runner = ServiceTestRunner(TWO_POD_YAML)
    runner.run([
        AdvanceCycles(1),
        SendTaskRunning("hello-0-server"),
        AdvanceCycles(1),
        SendTaskRunning("hello-1-server"),
        ExpectDeploymentComplete(),
    ])
    world = runner.run([
        SendTaskFailed("hello-0-server"),
        ExpectRecoveryStep("hello-0"),
        AdvanceCycles(1),
        SendTaskRunning("hello-0-server"),
        ExpectPlanStatus("recovery", Status.COMPLETE),
        ExpectTaskStateStored("hello-0-server", TaskState.RUNNING),
    ])
    # in-place (TRANSIENT) recovery relaunched the same task name twice
    assert len(world.agent.launches_of("hello-0-server")) == 2


def test_scheduler_restart_resumes_mid_deploy():
    runner = ServiceTestRunner(TWO_POD_YAML)
    runner.run([
        AdvanceCycles(1),
        ExpectLaunchedTasks("hello-0-server"),
    ])
    # restart the scheduler over the same persister while hello-0 is
    # still STARTING: the launch WAL must resume the step mid-flight
    # (no duplicate launch), and the deployment then finishes normally
    restarted = runner.restart()
    restarted.run([
        AdvanceCycles(1),
        ExpectNoLaunches(),
        ExpectStepStatus("deploy", "hello", "hello-0:[server]", Status.STARTING),
        SendTaskRunning("hello-0-server"),
        ExpectStepStatus("deploy", "hello", "hello-0:[server]", Status.COMPLETE),
        AdvanceCycles(1),
        ExpectLaunchedTasks("hello-1-server"),
        SendTaskRunning("hello-1-server"),
        ExpectDeploymentComplete(),
    ])


CANARY_YAML = """
name: canary-svc
pods:
  web:
    count: 3
    tasks:
      node:
        goal: RUNNING
        cmd: "sleep 1000"
        cpus: 0.1
        memory: 32
plans:
  deploy:
    strategy: canary
    phases:
      web-phase:
        strategy: canary
        pod: web
"""


def test_canary_waits_for_proceed():
    runner = ServiceTestRunner(CANARY_YAML)
    runner.run([
        AdvanceCycles(2),
        # canary: nothing launches until an operator proceeds
        ExpectNoLaunches(),
        ExpectPlanStatus("deploy", Status.WAITING),
        # two gates: the plan-level canary over phases, then the
        # phase-level canary over steps (reference: plan continue vs
        # plan continue <phase>, PlansQueries.java:47-231)
        PlanContinue("deploy"),
        PlanContinue("deploy", "web-phase"),
        ExpectLaunchedTasks("web-0-node"),
        SendTaskRunning("web-0-node"),
        AdvanceCycles(1),
        # canary strategy requires a second proceed before the rest
        ExpectNoLaunches(),
        PlanContinue("deploy", "web-phase"),
        ExpectLaunchedTasks("web-1-node"),
        SendTaskRunning("web-1-node"),
        AdvanceCycles(1),
        # after the canary count (2), remaining steps flow freely
        ExpectLaunchedTasks("web-2-node"),
        SendTaskRunning("web-2-node"),
        ExpectDeploymentComplete(),
    ])


def test_config_update_rolls_changed_pods():
    runner = ServiceTestRunner(TWO_POD_YAML)
    runner.run([
        AdvanceCycles(1),
        SendTaskRunning("hello-0-server"),
        AdvanceCycles(1),
        SendTaskRunning("hello-1-server"),
        ExpectDeploymentComplete(),
    ])
    # bump the command -> new target config -> update plan redeploys
    new_yaml = TWO_POD_YAML.replace("sleep 1000", "sleep 2000")
    updated = ServiceTestRunner(
        new_yaml,
        persister=runner.persister,
        hosts=runner.hosts,
    )
    updated.agent = runner.agent
    updated.inventory = runner.inventory
    updated.run([
        AdvanceCycles(1),
        # rolling update: instance 0 relaunched first, old task killed
        ExpectTaskKilled("hello-0-server"),
        SendTaskRunning("hello-0-server"),
        AdvanceCycles(1),
        ExpectTaskKilled("hello-1-server"),
        SendTaskRunning("hello-1-server"),
        ExpectPlanStatus("update", Status.COMPLETE),
    ])
    assert len(updated.agent.launches_of("hello-0-server")) == 2
    new_info = updated.agent.task_info_of("hello-0-server")
    assert "sleep 2000" in new_info.command


def test_orphaned_agent_task_is_swept():
    """A task alive on the agent that the store doesn't own (lost kill
    whose successor launched, or state loss) must be killed by the
    standalone orphan sweep (reference: kill-unneeded-tasks,
    DefaultScheduler.java:252-270)."""
    from dcos_commons_tpu.common import TaskInfo, new_task_id

    runner = ServiceTestRunner(TWO_POD_YAML)
    runner.run([
        AdvanceCycles(1),
        SendTaskRunning("hello-0-server"),
        AdvanceCycles(1),
        SendTaskRunning("hello-1-server"),
        ExpectDeploymentComplete(),
    ])
    scheduler = runner.world.scheduler
    rogue_id = new_task_id("hello-0-server")  # stale id for a known name
    runner.agent.launch_one(TaskInfo(name="hello-0-server", task_id=rogue_id))
    unknown_id = new_task_id("ghost-9-task")  # name the store never saw
    runner.agent.launch_one(TaskInfo(name="ghost-9-task", task_id=unknown_id))
    good_id = scheduler.state_store.fetch_task("hello-0-server").task_id
    scheduler.run_cycle()
    assert rogue_id in runner.agent.kills
    assert unknown_id in runner.agent.kills
    assert good_id not in runner.agent.kills
