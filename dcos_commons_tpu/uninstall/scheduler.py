"""UninstallScheduler: the event loop that tears the service down.

Reference: scheduler/uninstall/UninstallScheduler.java +
UninstallPlanFactory.java — selected by SchedulerBuilder when
SDK_UNINSTALL is set (SchedulerBuilder.java:331+); drives a plan of
kill -> unreserve -> deregister phases, then wipes all persisted
state.  A restart after completion rebuilds over empty state: every
phase is trivially complete, which IS the reference's "skeleton
scheduler" (FrameworkRunner.java:99-115,214-238) — the API serves a
COMPLETE deploy/uninstall plan so the package manager can finish.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, Optional

from dcos_commons_tpu.common import task_name_of
from dcos_commons_tpu.debug.trackers import OfferOutcomeTracker
from dcos_commons_tpu.metrics.registry import Metrics
from dcos_commons_tpu.plan.coordinator import DefaultPlanCoordinator
from dcos_commons_tpu.plan.phase import Phase
from dcos_commons_tpu.plan.plan import Plan
from dcos_commons_tpu.plan.plan_manager import DefaultPlanManager
from dcos_commons_tpu.plan.step import ActionStep
from dcos_commons_tpu.plan.strategy import SerialStrategy
from dcos_commons_tpu.runtime.reconciler import Reconciler
from dcos_commons_tpu.runtime.task_killer import TaskKiller

LOG = logging.getLogger(__name__)

UNINSTALL_PLAN_NAME = "uninstall"


class UninstallPlanFactory:
    def build(self, state_store, ledger) -> Plan:
        def kill_all(scheduler) -> bool:
            """Kill every known task; done when all are terminal and
            the agent reports nothing alive."""
            all_done = True
            for name, status in scheduler.state_store.fetch_statuses().items():
                if status.state.is_terminal:
                    continue
                scheduler.task_killer.kill(status.task_id)
                all_done = False
            # tasks the agent knows but the store lost (torn WAL, old
            # runs) die too — but ONLY in a standalone (whole-framework)
            # uninstall.  A namespaced multi-service removal sees the
            # SHARED agent's task set and must never touch ids other
            # services own (reference: single-service removal tears
            # down only that client's tasks, MultiServiceEventClient).
            owned = {
                info.task_id for info in scheduler.state_store.fetch_tasks()
            }
            for task_id in scheduler.agent.active_task_ids():
                if not scheduler._deregister and task_id not in owned:
                    continue
                scheduler.task_killer.kill(task_id)
                all_done = False
            return all_done

        def unreserve_all(scheduler) -> bool:
            """ResourceCleanupStep: release every ledger claim."""
            for reservation in scheduler.ledger.all():
                scheduler.ledger.release(reservation.reservation_id)
                scheduler.metrics.incr("operations.unreserve")
            return True

        def deregister(scheduler) -> bool:
            """DeregisterStep: drop the framework identity and wipe all
            persisted state (reference: FrameworkID cleared + ZK wiped,
            FrameworkRunner.java:147-155, PersisterUtils.clearAllData)."""
            if scheduler._deregister and scheduler.framework_store is not None:
                scheduler.framework_store.clear_framework_id()
            scheduler.wipe_state()
            return True

        return Plan(
            UNINSTALL_PLAN_NAME,
            [
                Phase("kill-tasks", [ActionStep("kill-all-tasks", kill_all)],
                      SerialStrategy()),
                Phase("unreserve-resources",
                      [ActionStep("unreserve-all", unreserve_all)],
                      SerialStrategy()),
                Phase("deregister", [ActionStep("deregister", deregister)],
                      SerialStrategy()),
            ],
            SerialStrategy(),
        )


class UninstallScheduler:
    """Duck-type compatible with DefaultScheduler for the HTTP API and
    sim harness (plans()/plan()/run_cycle()/stores)."""

    def __init__(
        self,
        spec,
        state_store,
        ledger,
        inventory,
        agent,
        persister,
        config_store=None,
        framework_store=None,
        metrics: Optional[Metrics] = None,
        namespace: str = "",
        deregister: bool = True,
    ):
        # multi-service removal tears down ONE namespaced service: it
        # wipes only its subtree and must not drop the shared framework
        # identity (reference: MultiServiceEventClient uninstall-and-
        # remove flow vs whole-framework uninstall)
        self._namespace = namespace
        self._deregister = deregister
        self.spec = spec
        self.state_store = state_store
        self.ledger = ledger
        self.inventory = inventory
        self.agent = agent
        self.persister = persister
        self.config_store = config_store
        self.framework_store = framework_store
        self.metrics = metrics or Metrics()
        self.outcome_tracker = OfferOutcomeTracker()
        self.task_killer = TaskKiller(agent)
        self.reconciler = Reconciler(state_store, agent)
        plan = UninstallPlanFactory().build(state_store, ledger)
        self.uninstall_manager = DefaultPlanManager(plan)
        # deploy_manager alias: /v1/health and tooling ask whether
        # "deployment" finished; during uninstall that IS the teardown
        self.deploy_manager = self.uninstall_manager
        self.coordinator = DefaultPlanCoordinator([self.uninstall_manager])
        self._stop = threading.Event()
        self._lock = threading.RLock()
        self._wiped = False

    # -- loop ---------------------------------------------------------

    def run_cycle(self) -> None:
        with self._lock:
            for status in self.agent.poll():
                self._process_status(status)
            if not self.reconciler.is_reconciled:
                # stale RUNNING statuses for tasks the agent lost would
                # wedge kill_all forever: synthesize LOST for them, as
                # the deploy scheduler does (runtime/reconciler.py)
                for status in self.reconciler.reconcile():
                    self._process_status(status)
            for step in self.coordinator.get_candidates():
                if isinstance(step, ActionStep):
                    step.execute(self)
            self.task_killer.retry_pending()

    def run_forever(self, interval_s: float = 0.5) -> threading.Thread:
        def loop():
            while not self._stop.is_set():
                try:
                    self.run_cycle()
                except Exception:
                    LOG.exception("uninstall cycle failed")
                self._stop.wait(interval_s)

        thread = threading.Thread(
            target=loop, name="uninstall-loop", daemon=True
        )
        thread.start()
        return thread

    def stop(self) -> None:
        self._stop.set()

    def _process_status(self, status) -> None:
        if self._wiped:
            return  # post-wipe stragglers have nowhere to go
        try:
            task_name = task_name_of(status.task_id)
        except ValueError:
            return
        self.state_store.store_status(task_name, status)
        self.task_killer.handle_status(status)
        for manager in self.coordinator.plan_managers:
            manager.update(status)

    def wipe_state(self) -> None:
        """Delete every persisted node of this service (the whole tree
        for a standalone service, only the namespace subtree in
        multi-service mode)."""
        from dcos_commons_tpu.storage.persister import wipe_namespace

        wipe_namespace(self.persister, self._namespace)
        self._wiped = True

    # -- API surface --------------------------------------------------

    @property
    def is_complete(self) -> bool:
        return self.uninstall_manager.get_plan().is_complete

    def plans(self) -> Dict[str, Plan]:
        plan = self.uninstall_manager.get_plan()
        # serve the teardown under both names: Cosmos-equivalent
        # tooling polls "deploy" for completion (reference skeleton
        # scheduler serves an empty COMPLETE deploy plan)
        return {UNINSTALL_PLAN_NAME: plan, "deploy": plan}

    def plan(self, name: str) -> Optional[Plan]:
        return self.plans().get(name)

    def restart_pod(self, pod_type: str, index: int, replace: bool = False):
        return []  # no pod verbs during uninstall

    def pause_pod(self, pod_type, index, tasks=None):
        return []

    def resume_pod(self, pod_type, index, tasks=None):
        return []
