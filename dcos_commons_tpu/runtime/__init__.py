"""L1 runtime: event-loop plumbing around the scheduler.

Reference: sdk/scheduler/.../framework/ — OfferProcessor.java (the
single offer thread + bounded queue), TaskKiller.java (async kill with
retries until terminal status), TokenBucket.java (revive rate limit),
ImplicitReconciler.java / ExplicitReconciler.java (status
reconciliation gating offers, AbstractScheduler.java:163-184).
"""

from dcos_commons_tpu.runtime.task_killer import TaskKiller
from dcos_commons_tpu.runtime.token_bucket import TokenBucket
from dcos_commons_tpu.runtime.reconciler import Reconciler

__all__ = ["Reconciler", "TaskKiller", "TokenBucket"]
