"""EvaluationOutcome: pass/fail with an explanation tree.

Reference: offer/evaluate/EvaluationOutcome.java — every stage returns
one of these, and the "why did placement fail" record they form is the
operator-facing feature SURVEY.md section 5.1 flags as the single most
loved: keep it.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List


@dataclass
class EvaluationOutcome:
    passed: bool
    source: str                     # stage / rule name
    reason: str
    children: List["EvaluationOutcome"] = field(default_factory=list)

    @staticmethod
    def ok(source: str, reason: str = "") -> "EvaluationOutcome":
        return EvaluationOutcome(True, source, reason or "passed")

    @staticmethod
    def fail(source: str, reason: str) -> "EvaluationOutcome":
        return EvaluationOutcome(False, source, reason)

    def to_dict(self) -> dict:
        return {
            "passed": self.passed,
            "source": self.source,
            "reason": self.reason,
            "children": [c.to_dict() for c in self.children],
        }

    def flatten(self, indent: int = 0) -> List[str]:
        mark = "PASS" if self.passed else "FAIL"
        lines = [f"{'  ' * indent}{mark} {self.source}: {self.reason}"]
        for child in self.children:
            lines.extend(child.flatten(indent + 1))
        return lines
