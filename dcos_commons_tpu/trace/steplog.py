"""Worker-side step telemetry: append-only JSONL in the sandbox.

The scheduler's flight recorder sees the control plane; the worker's
pjit step loop is invisible to it.  ``StepLog`` closes that gap from
the task side: each training/serving step appends one JSON line
(step index, wall seconds, tokens, seconds blocked waiting for the
gang before the step's first collective) to ``steplog.jsonl`` in the
task sandbox.  The agent's sandbox plumbing (``LocalProcessAgent.
steplog_of``) surfaces the file and the scheduler's ``/v1/debug/trace``
exporters merge it into the same timeline — per-host step lanes make
gang skew directly visible (host 3's ``blocked_s`` IS the skew the
other hosts imposed on it).

Telemetry must never take a worker down: write failures are counted
(``errors``) and otherwise ignored.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Callable, List, Optional, Tuple

STEPLOG_NAME = "steplog.jsonl"


class StepLog:
    """Appends one JSON record per step; flushes per record so a gang
    worker killed mid-run leaves a readable log."""

    def __init__(self, path: Optional[str] = None):
        # the scheduler's env contract puts every task in a sandbox
        # ($SANDBOX, agent/local.py); outside one, log to cwd
        self.path = path or os.path.join(
            os.environ.get("SANDBOX", "."), STEPLOG_NAME
        )
        self.errors = 0
        self._fh = None

    def record(self, step: int, **fields) -> None:
        entry = {"step": int(step), "t": time.time()}
        entry.update(fields)
        try:
            if self._fh is None:
                self._fh = open(self.path, "a", encoding="utf-8")
            self._fh.write(json.dumps(entry) + "\n")
            self._fh.flush()
        except (OSError, ValueError, TypeError):
            # telemetry is best-effort: a full disk or closed handle
            # must not kill the training step that produced the record
            self.errors += 1

    def close(self) -> None:
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                self.errors += 1
            self._fh = None


def _default_ready(result: Any) -> Any:
    """Block until a dispatched jax result is materialized.  Imported
    lazily: the scheduler-side steplog readers must not pull jax in."""
    import jax

    return jax.block_until_ready(result)


class InflightWindow:
    """Bounded async-dispatch window with per-step wall accounting.

    Under async dispatch the host runs ahead of the devices: step N's
    jit call returns in microseconds and the host only blocks when the
    window is full — on step N−k's result, not step N's.  Naive timing
    then records dispatch time as ``wall_s`` and the NEXT step's
    barrier probe absorbs this step's compute and reports it as gang
    skew (the trap PR 5 already hit once, solved then by blocking
    every step — which is exactly the serialization this window
    removes).  The books stay straight by billing each step the wall
    time between ITS result becoming ready and the previous step's:
    in a saturated pipeline that is precisely the device time the step
    added to the run, and during pipeline fill the first step absorbs
    the fill cost it incurred.  ``blocked_s`` stays whatever the
    caller measured BEFORE dispatching the step (the barrier probe
    meets the gang at dispatch order, so its wait is still the skew
    the slow host imposed at that point).

    ``window=0`` degenerates to the synchronous loop: every ``push``
    drains immediately and ``wall_s`` spans dispatch start to ready —
    byte-identical accounting to the pre-overlap worker.
    """

    def __init__(
        self,
        steplog: StepLog,
        window: int = 2,
        ready_fn: Callable[[Any], Any] = _default_ready,
    ):
        self.steplog = steplog
        self.window = max(0, int(window))
        self._ready = ready_fn
        self._pending: List[Tuple[int, Any, float, float, dict]] = []
        self._last_ready: Optional[float] = None
        self.drained = 0

    def push(
        self, step: int, result: Any, dispatched_t: float,
        blocked_s: float = 0.0, **fields,
    ) -> List[Tuple[int, Any]]:
        """Admit a dispatched step; drains (blocks on) the oldest
        steps beyond the window.  ``dispatched_t`` is when the step
        STARTED on the host (before its data fetch + dispatch), so the
        degenerate window=0 spelling times what the old synchronous
        loop timed.  Returns the [(step, ready result)] drained now.
        """
        self._pending.append(
            (int(step), result, float(dispatched_t), float(blocked_s),
             fields)
        )
        out: List[Tuple[int, Any]] = []
        while len(self._pending) > self.window:
            out.append(self._drain_one())
        return out

    def drain(self) -> List[Tuple[int, Any]]:
        """Drain every in-flight step (end of loop, or a fence before
        an action that must see the loop quiesced)."""
        out = []
        while self._pending:
            out.append(self._drain_one())
        return out

    def _drain_one(self) -> Tuple[int, Any]:
        step, result, t0, blocked_s, fields = self._pending.pop(0)
        self._ready(result)
        t_ready = time.time()
        # bill THIS step the wall clock since the previous step's
        # result was ready (or since its own dispatch, whichever is
        # later — an idle gap between steps is nobody's device time)
        since = t0 if self._last_ready is None else max(
            self._last_ready, t0
        )
        self._last_ready = t_ready
        self.steplog.record(
            step,
            wall_s=round(t_ready - since, 6),
            blocked_s=round(blocked_s, 6),
            **fields,
        )
        self.drained += 1
        return step, result


def read_steplog(path: str) -> List[dict]:
    """Parse a steplog file; malformed/truncated lines (a worker killed
    mid-write) are skipped, valid records around them survive."""
    out: List[dict] = []
    try:
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    record = json.loads(line)
                except ValueError:
                    continue
                if isinstance(record, dict):
                    out.append(record)
    except OSError:
        return []
    return out
