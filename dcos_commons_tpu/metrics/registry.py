"""Counter/gauge registry with Prometheus text exposition.

Reference: metrics/Metrics.java — counters incremented on the hot path
(offers received/processed, revives, declines, suppresses, operation
types, task statuses) and scraped at /v1/metrics/prometheus.  StatsD
push is env-gated as in the reference (STATSD_UDP_HOST/PORT,
Metrics.java:74-79).
"""

from __future__ import annotations

import os
import socket
import threading
import time
from typing import Callable, Dict, Optional


def percentile(ordered, q: int) -> float:
    """Nearest-rank percentile over an ASCENDING-sorted sequence —
    the one convention shared by the registry's timer aggregates, the
    serve engine's TTFT gauges, and bench percentiles (three copies
    of this formula once disagreed off-by-one at small counts)."""
    n = len(ordered)
    return ordered[min(n - 1, max(0, -(-q * n // 100) - 1))]


class Metrics:
    def __init__(self) -> None:
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, Callable[[], float]] = {}
        self._timers: Dict[str, list] = {}
        self._timer_totals: Dict[str, int] = {}
        self._lock = threading.Lock()
        self._statsd: Optional[socket.socket] = None
        self._statsd_addr = None
        host = os.environ.get("STATSD_UDP_HOST")
        port = os.environ.get("STATSD_UDP_PORT")
        if host and port:
            self._statsd = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            self._statsd_addr = (host, int(port))

    def incr(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0.0) + value
        if self._statsd is not None:
            try:
                self._statsd.sendto(
                    f"{name}:{value}|c".encode(), self._statsd_addr
                )
            except OSError:
                pass

    def gauge(self, name: str, fn: Callable[[], float]) -> None:
        with self._lock:
            self._gauges[name] = fn

    def time(self, name: str):
        """Context manager recording wall seconds (offers.process timer)."""
        registry = self

        class _Timer:
            def __enter__(self):
                self._t0 = time.monotonic()
                return self

            def __exit__(self, *exc):
                elapsed = time.monotonic() - self._t0
                with registry._lock:
                    registry._timers.setdefault(name, []).append(elapsed)
                    del registry._timers[name][:-256]  # ring buffer
                    registry._timer_totals[name] = (
                        registry._timer_totals.get(name, 0) + 1
                    )
                if registry._statsd is not None:
                    # timers push like counters do (reference:
                    # Metrics.getTimer — StatsD timing datagrams in
                    # milliseconds, the `|ms` type)
                    try:
                        registry._statsd.sendto(
                            f"{name}:{elapsed * 1000.0:.3f}|ms".encode(),
                            registry._statsd_addr,
                        )
                    except OSError:
                        pass
                return False

        return _Timer()

    def counters(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._counters)

    def timer_count(self, name: str) -> int:
        """Total recordings of one timer since process start — NOT
        capped by the 256-sample ring, so callers can window samples
        across a phase boundary without index drift."""
        with self._lock:
            return self._timer_totals.get(name, 0)

    def timer_samples(self, name: str, since_count: int = 0) -> list:
        """Copy of the retained samples (newest-last, last 256) for
        one timer, optionally only those recorded after a prior
        ``timer_count()`` reading.  When the ring has trimmed past the
        requested boundary, returns what survives — the newest
        samples, which is what phase-window callers want."""
        with self._lock:
            samples = list(self._timers.get(name, ()))
            fresh = self._timer_totals.get(name, 0) - since_count
        if fresh <= 0:
            return []
        return samples[-fresh:] if fresh < len(samples) else samples

    def snapshot(self) -> Dict[str, float]:
        out = self.counters()
        with self._lock:
            gauges = dict(self._gauges)
            for name, samples in self._timers.items():
                if samples:
                    ordered = sorted(samples)
                    n = len(ordered)
                    mean = sum(ordered) / n
                    out[f"{name}.count"] = float(n)
                    out[f"{name}.min_s"] = ordered[0]
                    out[f"{name}.mean_s"] = mean
                    out[f"{name}.avg_s"] = mean  # legacy alias
                    out[f"{name}.max_s"] = ordered[-1]
                    # nearest-rank p95 over the ring buffer window
                    out[f"{name}.p95_s"] = percentile(ordered, 95)
        for name, fn in gauges.items():
            try:
                out[name] = float(fn())
            except Exception:  # sdklint: disable=swallowed-exception — one broken gauge must not break the whole snapshot/scrape
                pass
        return out

    def prometheus(self) -> str:
        """Prometheus text format (reference: Metrics.java:85-97).

        ``incr()`` entries are monotonic and expose as ``counter`` (so
        ``rate()`` works on them downstream); timer aggregates and
        registered gauges expose as ``gauge``."""
        with self._lock:
            counter_names = set(self._counters)
        lines = []
        for name, value in sorted(self.snapshot().items()):
            metric = name.replace(".", "_").replace("-", "_").lower()
            kind = "counter" if name in counter_names else "gauge"
            lines.append(f"# TYPE {metric} {kind}")
            lines.append(f"{metric} {value}")
        return "\n".join(lines) + "\n"
