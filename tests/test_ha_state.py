"""HA state backend: replication, promotion, fencing, failover e2e.

Reference: the reference's durability story is CuratorPersister over a
ZooKeeper *ensemble* (curator/CuratorPersister.java:43-110) — the
state backend has no single point of failure.  These tests prove the
rebuild's primary/standby StateServer pair (storage/replication.py)
gives the same property: a standby tails the primary's mutation log,
an operator promotion mints a fencing epoch, a partitioned stale
primary cannot split-brain, and the headline e2e — kill the primary
state server MID-DEPLOY, promote the standby, the lease-driven
scheduler reconnects and the plan completes without restarting.
"""

import json
import os
import time
import urllib.request

import pytest

from dcos_commons_tpu.storage.persister import (
    MemPersister,
    PersisterError,
    SetOp,
)
from dcos_commons_tpu.storage.remote import (
    ROLE_FENCED,
    RemoteLocker,
    RemotePersister,
    StateServer,
)
from dcos_commons_tpu.storage.replication import ReplicationLog

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module", autouse=True)
def _racecheck_probes():
    """Dynamic race probes (SDKLINT_RACECHECK=1): the replication
    puller applies entries on its own thread while the serving side
    reads — watch the replication classes' shared-write set so any
    unordered pair fails the run.  No-op in the fast tier."""
    from dcos_commons_tpu.storage.remote import StateServer
    from dcos_commons_tpu.storage.replication import ReplicationLog

    from conftest import racecheck_watch_guard

    yield from racecheck_watch_guard(StateServer, ReplicationLog)


def wait_until(check, timeout_s=10.0, interval_s=0.05, what="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if check():
            return
        time.sleep(interval_s)
    raise AssertionError(f"timed out waiting for {what}")


def user_dump(persister):
    """Tree minus the server-internal /__cluster__ namespace."""
    return {
        path: value
        for path, value in persister.dump().items()
        if not path.startswith("/__cluster__")
    }


# -- in-process replication semantics ---------------------------------


def test_standby_replicates_and_promote_serves_identical_tree():
    """Writes stream to the standby (snapshot bootstrap + tail);
    promotion mints epoch+1 and serves the identical user tree."""
    primary = StateServer(MemPersister()).start()
    try:
        client = RemotePersister(primary.url)
        client.set("/svc/a", b"1")
        client.apply([SetOp("/svc/b", b"2"), SetOp("/svc/c/d", b"3")])
        standby = StateServer(
            MemPersister(), replicate_from=primary.url
        ).start()
        try:
            # snapshot bootstrap covers pre-standby writes...
            wait_until(
                lambda: user_dump(standby._backend) == user_dump(
                    primary._backend
                ),
                what="snapshot bootstrap",
            )
            # ...and the tail covers live ones, including deletes
            client.set("/svc/e", b"4")
            client.recursive_delete("/svc/a")
            wait_until(
                lambda: user_dump(standby._backend) == user_dump(
                    primary._backend
                ),
                what="live tail",
            )
            out = RemotePersister(standby.url)._call("/v1/repl/promote", {})
            assert out["epoch"] == 2
            promoted = RemotePersister(standby.url)
            assert promoted.get("/svc/e") == b"4"
            assert promoted.get_or_none("/svc/a") is None
            assert promoted.get("/svc/c/d") == b"3"
        finally:
            standby.stop()
    finally:
        primary.stop()


@pytest.mark.slow
def test_standby_rejects_kv_and_clients_rotate():
    """A standby answers kv with 503; a multi-URL client finds the
    primary regardless of list order."""
    primary = StateServer(MemPersister()).start()
    standby = StateServer(MemPersister(), replicate_from=primary.url).start()
    try:
        with pytest.raises(PersisterError, match="not primary"):
            RemotePersister(standby.url).set("/x", b"1")
        # standby listed FIRST: the client rotates to the primary
        multi = RemotePersister(f"{standby.url},{primary.url}")
        multi.set("/x", b"1")
        assert multi.get("/x") == b"1"
    finally:
        standby.stop()
        primary.stop()


def test_bounded_sync_log_semantics():
    """No standby -> writes don't block; a FRESH attach is lagging
    until its ack first reaches the tip (bootstrap replay never gates
    live writes, advisor r5); an in-sync standby that stalls is marked
    lagging after the sync timeout; catching up clears it."""
    log = ReplicationLog(sync_timeout_s=0.2)
    seq = log.append([{"op": "set", "path": "/a", "value": ""}])
    t0 = time.monotonic()
    assert log.wait_replicated(seq) is False  # nobody attached: no wait
    assert time.monotonic() - t0 < 0.1
    # a standby attaches by pulling: lagging (excluded from the
    # barrier) until it proves it reached the tip
    out = log.pull(from_seq=1, wait_s=0)
    assert [e["seq"] for e in out["entries"]] == [1]
    assert log.status()["standby_lagging"] is True
    seq2 = log.append([{"op": "set", "path": "/b", "value": ""}])
    # attached mid-bootstrap: writes do NOT block on its replay
    t0 = time.monotonic()
    assert log.wait_replicated(seq2) is False
    assert time.monotonic() - t0 < 0.1
    # catch-up (pull acking the tip) earns the barrier
    log.pull(from_seq=seq2 + 1, wait_s=0)
    assert log.status()["standby_lagging"] is False
    # in-sync but not acking: blocks for the timeout, then lagging
    seq3 = log.append([{"op": "set", "path": "/c", "value": ""}])
    t0 = time.monotonic()
    assert log.wait_replicated(seq3) is False
    assert 0.15 <= time.monotonic() - t0 < 1.0
    assert log.status()["standby_lagging"] is True
    # lagging: subsequent writes do NOT block
    seq4 = log.append([{"op": "set", "path": "/d", "value": ""}])
    t0 = time.monotonic()
    assert log.wait_replicated(seq4) is False
    assert time.monotonic() - t0 < 0.1
    # catch-up (pull acking the tip) clears the flag
    log.pull(from_seq=seq4 + 1, wait_s=0)
    assert log.status()["standby_lagging"] is False
    seq5 = log.append([{"op": "set", "path": "/e", "value": ""}])
    # acked promptly -> wait_replicated returns True
    import threading

    threading.Timer(0.05, lambda: log.pull(seq5 + 1, 0)).start()
    assert log.wait_replicated(seq5) is True


def test_ring_trim_and_fresh_primary_force_resnapshot():
    """Continuity that cannot be proven -> snapshot_needed: both a
    trimmed ring and a restarted (empty-ring) primary."""
    log = ReplicationLog(max_entries=4)
    for i in range(10):
        log.append([{"op": "set", "path": f"/k{i}", "value": ""}])
    assert log.pull(from_seq=2, wait_s=0)["snapshot_needed"] is True
    assert "entries" in log.pull(from_seq=8, wait_s=0)
    fresh = ReplicationLog()
    # standby was at seq 500, primary restarted with an empty ring
    assert fresh.pull(from_seq=501, wait_s=0)["snapshot_needed"] is True


def test_overshooting_pull_never_inflates_ack_watermark():
    """A standby ahead of a restarted primary's ring (from_seq above
    it) must not ack anything: bounded-sync would otherwise claim
    writes replicated that the standby never copied."""
    log = ReplicationLog(sync_timeout_s=0.2)
    out = log.pull(from_seq=106, wait_s=0)  # standby from a prior ring
    assert out["snapshot_needed"] is True
    assert log.status()["acked_seq"] == 0
    seq = log.append([{"op": "set", "path": "/a", "value": ""}])
    # the watermark was not inflated: this write is NOT "replicated"
    assert log.wait_replicated(seq) is False
    # and the behind-standby is marked lagging so writes don't block
    assert log.status()["standby_lagging"] is True


def test_promote_refuses_never_synced_standby_and_fenced_server():
    """An empty standby promotes to an EMPTY tree at a colliding
    epoch — refused without an explicit override; a fenced server
    carries a stale tree — never promotable."""
    primary = StateServer(MemPersister()).start()
    try:
        RemotePersister(primary.url).set("/a", b"v1")
        # standby pointed at a DEAD url: it can never sync
        dead = StateServer(
            MemPersister(), replicate_from="http://127.0.0.1:9"
        ).start()
        try:
            with pytest.raises(PersisterError, match="never replicated"):
                RemotePersister(dead.url)._call("/v1/repl/promote", {})
            # explicit epoch overrides (operator bootstrap escape hatch)
            out = RemotePersister(dead.url)._call(
                "/v1/repl/promote", {"epoch": 7}
            )
            assert out["epoch"] == 7
        finally:
            dead.stop()
        # fence the original primary, then try to promote it back
        primary.check_fence(9)
        assert primary._role == ROLE_FENCED
        with pytest.raises(PersisterError, match="only promote a standby"):
            primary.promote()
    finally:
        primary.stop()


def test_stale_primary_fences_itself_on_rotation():
    """Split-brain guard: a client that has seen the new epoch fences
    the old primary the moment it rotates back to it."""
    primary = StateServer(MemPersister()).start()
    standby = StateServer(MemPersister(), replicate_from=primary.url).start()
    try:
        RemotePersister(primary.url).set("/a", b"v1")
        wait_until(
            lambda: user_dump(standby._backend) == user_dump(
                primary._backend
            ),
            what="replication",
        )
        RemotePersister(standby.url)._call("/v1/repl/promote", {})
        client = RemotePersister(f"{standby.url},{primary.url}")
        assert client.get("/a") == b"v1"  # learns epoch 2 from new primary
        standby.stop()  # new primary dies
        with pytest.raises(PersisterError):
            client.set("/a", b"v2")  # rotation carries fence 2
        assert primary._role == ROLE_FENCED
        # fenced forever: even a fence-naive client gets 503 now
        with pytest.raises(PersisterError, match="not primary"):
            RemotePersister(primary.url).set("/a", b"v3")
    finally:
        primary.stop()


def test_fenced_primary_stays_fenced_across_restart(tmp_path):
    """A supervisor auto-restarting a fenced primary must NOT
    resurrect it as primary: it adopted the new primary's epoch, so
    as a primary it would be indistinguishable from the real one."""
    from dcos_commons_tpu.storage.file_persister import FileWalPersister

    data = str(tmp_path / "state")
    server = StateServer(FileWalPersister(data)).start()
    RemotePersister(server.url).set("/a", b"v1")
    server.check_fence(5)
    assert server._role == ROLE_FENCED
    server.stop()
    # supervisor restart, same flags (no --standby-of)
    reborn = StateServer(FileWalPersister(data)).start()
    try:
        assert reborn._role == ROLE_FENCED
        with pytest.raises(PersisterError, match="not primary"):
            RemotePersister(reborn.url).set("/a", b"v2")
    finally:
        reborn.stop()


def test_divergence_triggers_snapshot_repair():
    """An entry that fails to apply on the standby (trees diverged)
    falls back to snapshot repair instead of wedging the tail."""
    primary = StateServer(MemPersister()).start()
    standby = StateServer(MemPersister(), replicate_from=primary.url).start()
    try:
        client = RemotePersister(primary.url)
        client.set("/svc/x", b"1")
        client.set("/svc/y", b"2")
        wait_until(
            lambda: standby._backend.get_or_none("/svc/y") == b"2",
            what="initial replication",
        )
        # poison the standby: drop a node behind the tail's back
        standby._backend.recursive_delete("/svc/x")
        client.recursive_delete("/svc/x")  # DeleteOp now fails there
        client.set("/svc/z", b"3")
        wait_until(
            lambda: user_dump(standby._backend) == user_dump(
                primary._backend
            ),
            timeout_s=15.0,
            what="snapshot repair",
        )
    finally:
        standby.stop()
        primary.stop()


@pytest.mark.slow
def test_lease_survives_failover(tmp_path):
    """The scheduler instance lease lives IN the replicated tree: the
    holder keeps renewing against the promoted standby, and a rival
    still cannot take the lease after failover."""
    primary = StateServer(MemPersister()).start()
    standby = StateServer(MemPersister(), replicate_from=primary.url).start()
    lost = []
    locker = RemoteLocker(
        f"{primary.url},{standby.url}", name="svc", owner="sched-a",
        ttl_s=3.0,
    )
    locker.on_lost = lost.append
    try:
        assert locker.acquire()
        wait_until(
            lambda: standby._backend.exists("/__cluster__/leases/svc"),
            what="lease replication",
        )
        primary.stop()  # hard death of the primary state server
        RemotePersister(standby.url)._call("/v1/repl/promote", {})
        time.sleep(2.5)  # multiple renewal intervals against new primary
        assert lost == [], f"lease lost during failover: {lost}"
        rival = RemoteLocker(
            f"{primary.url},{standby.url}", name="svc", owner="sched-b",
            ttl_s=3.0,
        )
        assert rival.acquire() is False
    finally:
        locker.release()
        standby.stop()


@pytest.mark.slow
def test_standby_restart_resumes_from_persisted_seq(tmp_path):
    """A standby's applied seq is durable: after a standby restart it
    tails from where it left off (same primary ring) and converges."""
    from dcos_commons_tpu.storage.file_persister import FileWalPersister

    primary = StateServer(
        FileWalPersister(str(tmp_path / "primary"))
    ).start()
    try:
        client = RemotePersister(primary.url)
        client.set("/svc/a", b"1")
        standby = StateServer(
            FileWalPersister(str(tmp_path / "standby")),
            replicate_from=primary.url,
        ).start()
        wait_until(
            lambda: standby._backend.get_or_none("/svc/a") == b"1",
            what="first replication",
        )
        applied_before = standby._tail.applied_seq
        standby.stop()
        client.set("/svc/b", b"2")  # written while the standby is down
        standby2 = StateServer(
            FileWalPersister(str(tmp_path / "standby")),
            replicate_from=primary.url,
        ).start()
        try:
            assert standby2._tail.applied_seq == applied_before
            wait_until(
                lambda: standby2._backend.get_or_none("/svc/b") == b"2",
                what="catch-up after restart",
            )
        finally:
            standby2.stop()
    finally:
        primary.stop()


def test_per_puller_watermarks_never_cross():
    """N standbys, each with its OWN ack watermark (advisor r4 asked
    for exactly this): the fast standby's acks must not stand in for
    the slow one's — bounded-sync passes only when EVERY in-sync
    standby copied the write, so promoting ANY of them keeps every
    acked write."""
    from dcos_commons_tpu.storage.replication import ATTACH_WINDOW_S

    log = ReplicationLog(sync_timeout_s=0.2)
    log.append([{"op": "set", "path": "/a", "value": ""}])
    out = log.pull(from_seq=1, wait_s=0, puller_id="standby-a")
    assert [e["seq"] for e in out["entries"]] == [1]
    out = log.pull(from_seq=1, wait_s=0, puller_id="standby-b")
    assert [e["seq"] for e in out["entries"]] == [1]
    assert log.status()["standby_count"] == 2
    # both fresh attaches are lagging (bootstrap, advisor r5): neither
    # has earned the barrier, so neither gates writes yet
    assert log.status()["standbys"]["standby-a"]["lagging"] is True
    assert log.status()["standbys"]["standby-b"]["lagging"] is True
    # A acks seq 1 (the tip) and earns the barrier; the conservative
    # watermark (min over EVERY attached standby) stays at B's 0
    log.pull(from_seq=2, wait_s=0, puller_id="standby-a")
    assert log.status()["acked_seq"] == 0
    assert log.status()["standbys"]["standby-a"]["acked"] == 1
    assert log.status()["standbys"]["standby-a"]["lagging"] is False
    # B acks the tip too: in-sync, the barrier now includes it
    log.pull(from_seq=2, wait_s=0, puller_id="standby-b")
    assert log.status()["standbys"]["standby-b"]["lagging"] is False
    seq = log.append([{"op": "set", "path": "/b", "value": ""}])
    # a acks BEFORE the barrier; b (in-sync) never does: the barrier
    # still fails — an any-of ack would lose this write if b were
    # promoted — and ONLY the straggler is marked lagging
    # (deterministic: no timer races the sync timeout)
    log.pull(from_seq=seq + 1, wait_s=0, puller_id="standby-a")
    assert log.wait_replicated(seq) is False
    assert log.status()["standbys"]["standby-a"]["lagging"] is False
    assert log.status()["standbys"]["standby-b"]["lagging"] is True
    # with b excluded, a's acks alone satisfy the barrier
    seq2 = log.append([{"op": "set", "path": "/c", "value": ""}])
    log.pull(from_seq=seq2 + 1, wait_s=0, puller_id="standby-a")
    assert log.wait_replicated(seq2) is True
    # b catches up to the tip: lagging clears, barrier includes it again
    log.pull(from_seq=seq2 + 1, wait_s=0, puller_id="standby-b")
    assert log.status()["standbys"]["standby-b"]["lagging"] is False
    assert log.status()["acked_seq"] == seq2
    # a RESTARTED standby with a STABLE id that wiped its tree pulls
    # from seq 1 again: its old watermark must drop — promoting it
    # mid-catch-up must not count old acks (review r5) — and it leaves
    # the barrier while replaying (its replay must not stall writes)
    log.pull(from_seq=1, wait_s=0, puller_id="standby-a")
    assert log.status()["standbys"]["standby-a"]["acked"] == 0
    assert log.status()["standbys"]["standby-a"]["lagging"] is True
    # a dies: pruned after the attach window, b alone gates the barrier
    log._pullers["standby-a"]["last_pull"] -= ATTACH_WINDOW_S + 1.0
    assert log.status()["standby_count"] == 1
    # a RETURNING puller restarts at acked 0 (its tree may have been
    # wiped since) and lagging: it re-earns the barrier by pulling
    log.pull(from_seq=1, wait_s=0, puller_id="standby-a")
    assert log.status()["standbys"]["standby-a"]["acked"] == 0
    assert log.status()["standbys"]["standby-a"]["lagging"] is True


@pytest.mark.slow
def test_two_live_standbys_both_replicate_and_either_promotes():
    """E2e: two --standby-of servers stream the same primary
    concurrently; each holds the full tree, and promoting one of them
    serves it (the ensemble property: any replica can take over)."""
    primary = StateServer(MemPersister()).start()
    first = StateServer(MemPersister(), replicate_from=primary.url).start()
    second = StateServer(MemPersister(), replicate_from=primary.url).start()
    try:
        client = RemotePersister(primary.url)
        client.set("/svc/a", b"1")
        for standby in (first, second):
            wait_until(
                lambda s=standby: user_dump(s._backend) == user_dump(
                    primary._backend
                ),
                what="both standbys bootstrap",
            )
        status = RemotePersister(primary.url)._call("/v1/repl/status", {})
        assert status["standby_count"] == 2
        client.set("/svc/b", b"2")
        for standby in (first, second):
            wait_until(
                lambda s=standby: s._backend.get_or_none("/svc/b") == b"2",
                what="both standbys stream",
            )
        # promote the SECOND; the full tree is there
        out = RemotePersister(second.url)._call("/v1/repl/promote", {})
        assert out["epoch"] == 2
        promoted = RemotePersister(second.url)
        assert promoted.get("/svc/a") == b"1"
        assert promoted.get("/svc/b") == b"2"
    finally:
        second.stop()
        first.stop()
        primary.stop()


@pytest.mark.slow
def test_ex_primary_rejoins_via_full_snapshot(tmp_path):
    """A promoted standby's primary-life writes never advance its
    applied seq: if it is later fenced and rejoins as a standby, a
    surviving stale applied value could line up with the new primary's
    ring and resume the tail WITHOUT snapshot repair — silently
    keeping divergent unreplicated writes (advisor r4).  promote()
    deletes the applied marker, so the rejoin always bootstraps from a
    full snapshot and the divergent write is gone."""
    from dcos_commons_tpu.storage.file_persister import FileWalPersister
    from dcos_commons_tpu.storage.replication import StandbyTail

    a = StateServer(MemPersister()).start()
    b_dir = str(tmp_path / "b")
    try:
        RemotePersister(a.url).set("/svc/a", b"1")
        b = StateServer(
            FileWalPersister(b_dir), replicate_from=a.url
        ).start()
        wait_until(
            lambda: b._backend.get_or_none("/svc/a") == b"1",
            what="standby sync",
        )
        assert b._backend.exists(StandbyTail.APPLIED_NODE)
        RemotePersister(b.url)._call("/v1/repl/promote", {})
        # the applied marker is reset at promotion: primary-life
        # writes would never update it
        assert b._backend.get_or_none(StandbyTail.APPLIED_NODE) is None
        # divergent primary-life write on b, then b is superseded
        RemotePersister(b.url).set("/svc/divergent", b"x")
        b.check_fence(9)
        b.stop()
    finally:
        a.stop()
    # a NEW primary with its own history; b rejoins as its standby
    c = StateServer(MemPersister()).start()
    try:
        client = RemotePersister(c.url)
        client.set("/svc/a", b"1")
        client.set("/svc/c", b"3")
        b2 = StateServer(
            FileWalPersister(b_dir), replicate_from=c.url
        ).start()
        try:
            # bootstrap was a FULL snapshot: trees equal, divergent gone
            from dcos_commons_tpu.storage.replication import dump_tree

            def user_tree(persister):
                return {
                    path: value for path, value in dump_tree(persister)
                    if not path.startswith("/__cluster__")
                }

            wait_until(
                lambda: user_tree(b2._backend) == user_tree(c._backend),
                what="full-snapshot rejoin",
            )
            assert b2._backend.get_or_none("/svc/divergent") is None
        finally:
            b2.stop()
    finally:
        c.stop()


@pytest.mark.slow
def test_repointed_standby_forces_snapshot_on_stream_mismatch(tmp_path):
    """Seq numbers are only comparable within ONE primary's stream: a
    standby of X repointed at Y (whose ring happens to cover the
    standby's next seq) must NOT resume the tail — Y's entries would
    apply onto X's divergent tree silently.  The persisted stream id
    catches what the numeric continuity check cannot."""
    from dcos_commons_tpu.storage.file_persister import FileWalPersister
    from dcos_commons_tpu.storage.replication import dump_tree

    def user_tree(persister):
        return {
            path: value for path, value in dump_tree(persister)
            if not path.startswith("/__cluster__")
        }

    s_dir = str(tmp_path / "standby")
    x = StateServer(MemPersister()).start()
    try:
        RemotePersister(x.url).set("/svc/from-x", b"1")
        s = StateServer(
            FileWalPersister(s_dir), replicate_from=x.url
        ).start()
        wait_until(
            lambda: s._backend.get_or_none("/svc/from-x") == b"1",
            what="sync from X",
        )
        applied = s._tail.applied_seq
        s.stop()
    finally:
        x.stop()
    # Y's ring covers seq applied+1: numeric continuity would pass
    y = StateServer(MemPersister()).start()
    try:
        client = RemotePersister(y.url)
        for i in range(applied + 2):
            client.set(f"/svc/from-y{i}", b"y")
        s2 = StateServer(
            FileWalPersister(s_dir), replicate_from=y.url
        ).start()
        try:
            wait_until(
                lambda: user_tree(s2._backend) == user_tree(y._backend),
                what="snapshot repair on stream mismatch",
            )
            # X's write is GONE — the tail did not graft Y onto X
            assert s2._backend.get_or_none("/svc/from-x") is None
        finally:
            s2.stop()
    finally:
        y.stop()


def test_pull_route_requires_standby_id():
    """Anonymous pullers would collide as "" and bypass the
    single-puller guard entirely."""
    primary = StateServer(MemPersister()).start()
    try:
        with pytest.raises(PersisterError, match="standby_id"):
            RemotePersister(primary.url)._call(
                "/v1/repl/pull", {"from_seq": 1, "wait_s": 0}
            )
    finally:
        primary.stop()


def test_standby_tail_distrusts_applied_seq_on_fenced_tree(tmp_path):
    """Belt-and-braces for the same hazard: a tree carrying a fenced
    marker lived a primary life after its applied seq was written, so
    the tail must bootstrap from snapshot even if the marker-delete in
    promote() was lost (e.g. crash between role flip and delete)."""
    from dcos_commons_tpu.storage.file_persister import FileWalPersister
    from dcos_commons_tpu.storage.remote import FENCED_NODE
    from dcos_commons_tpu.storage.replication import StandbyTail

    backend = FileWalPersister(str(tmp_path / "tree"))
    backend.set(StandbyTail.APPLIED_NODE, b"17")
    backend.set(FENCED_NODE, b"9")
    import threading

    tail = StandbyTail(backend, threading.Lock(), "http://127.0.0.1:9")
    assert tail.applied_seq == 0  # forces snapshot bootstrap


# -- process-level failover e2e ---------------------------------------


HA_SVC_YAML = """
name: hasvc
pods:
  app:
    count: 3
    placement: 'max-per-host:1'
    tasks:
      server:
        goal: RUNNING
        cmd: "echo serving > out.txt && sleep 180"
        cpus: 0.1
        memory: 32
"""


def _write_topology(path, agents):
    lines = ["hosts:"]
    for agent in agents:
        lines += [
            f"  - host_id: {agent.host_id}",
            f"    agent_url: {agent.url}",
            "    cpus: 4.0",
            "    memory_mb: 8192",
        ]
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")


def _post(url, route, body=None):
    req = urllib.request.Request(
        url + route, data=json.dumps(body or {}).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    with urllib.request.urlopen(req, timeout=10) as resp:
        return json.loads(resp.read())


@pytest.mark.slow
def test_primary_death_mid_deploy_promote_plan_completes(tmp_path):
    """THE failover e2e (VERDICT r3 #1): real agent daemons, a real
    primary+standby state-server pair, a real scheduler process on
    --state-url "primary,standby".  The primary is SIGKILLed while the
    deploy plan is mid-flight; the standby is promoted; the SAME
    scheduler process rides through (lease renewed against the new
    primary, writes rotate over) and the plan completes."""
    from dcos_commons_tpu.testing.integration import (
        AgentProcess,
        SchedulerProcess,
        reap_orphan_tasks,
        start_state_server,
    )

    agents = [
        AgentProcess(f"h{i}", str(tmp_path / f"agent-{i}"), REPO)
        for i in range(3)
    ]
    state_a = state_b = sched = None
    log_a = log_b = None
    try:
        svc = tmp_path / "svc.yml"
        svc.write_text(HA_SVC_YAML)
        topology = tmp_path / "topology.yml"
        _write_topology(str(topology), agents)
        state_a, url_a, log_a = start_state_server(
            str(tmp_path / "state-a"), REPO
        )
        state_b, url_b, log_b = start_state_server(
            str(tmp_path / "state-b"), REPO, standby_of=url_a
        )
        sched = SchedulerProcess(
            str(svc), str(topology), str(tmp_path / "sched"),
            env={"ENABLE_BACKOFF": "false", "STATE_LEASE_TTL_S": "10"},
            repo_root=REPO,
            extra_args=["--state-url", f"{url_a},{url_b}"],
        )
        client = sched.client()
        # deterministically mid-deploy: first pod up, then the operator
        # interrupts the plan (WAITING) so it CANNOT complete before
        # the failover happens
        client.wait_for_task_state(
            "app-0-server", "TASK_RUNNING", timeout_s=60
        )
        client.post("/v1/plans/deploy/interrupt")
        assert client.plan_status("deploy") != "COMPLETE"

        state_a.kill()  # primary dies hard, mid-deploy
        state_a.wait(timeout=10)
        _post(url_b, "/v1/repl/promote")  # operator promotes standby

        # plan verbs and the rest of the rollout now run against the
        # NEW primary through the same scheduler process
        client.post("/v1/plans/deploy/continue")
        client.wait_for_completed_deployment(timeout_s=120)
        # the SAME scheduler process rode through the failover
        assert sched.process.poll() is None, "scheduler process died"
        status = _post(url_b, "/v1/repl/status")
        assert status["role"] == "primary" and status["epoch"] >= 2
    finally:
        if sched is not None:
            sched.terminate()
        reap_orphan_tasks(agents)
        for agent in agents:
            agent.stop()
        for proc, log in ((state_a, log_a), (state_b, log_b)):
            if proc is not None and proc.poll() is None:
                proc.terminate()
                proc.wait(timeout=10)
            if log is not None:
                log.close()


@pytest.mark.slow
def test_promote_cli_verb(tmp_path):
    """`state-server --promote URL --fence-old URL` drives the same
    failover from a shell; a dead old primary is a warning, not an
    error."""
    from dcos_commons_tpu.testing.integration import (
        promote_state_server,
        start_state_server,
    )

    state_a = state_b = None
    log_a = log_b = None
    try:
        state_a, url_a, log_a = start_state_server(
            str(tmp_path / "state-a"), REPO
        )
        state_b, url_b, log_b = start_state_server(
            str(tmp_path / "state-b"), REPO, standby_of=url_a
        )
        RemotePersister(url_a).set("/k", b"v")
        wait_until(
            lambda: RemotePersister(url_b)._call(
                "/v1/repl/status", {}
            )["applied_seq"] >= 1,
            what="replication",
        )
        state_a.kill()
        state_a.wait(timeout=10)
        promote_state_server(url_b, fence_old=url_a, repo_root=REPO)
        promoted = RemotePersister(url_b)
        assert promoted.get("/k") == b"v"
        promoted.set("/k2", b"v2")  # accepts writes as primary
        assert promoted._call("/v1/repl/status", {})["epoch"] >= 2
    finally:
        for proc, log in ((state_a, log_a), (state_b, log_b)):
            if proc is not None and proc.poll() is None:
                proc.terminate()
                proc.wait(timeout=10)
            if log is not None:
                log.close()


def test_primary_refuses_ack_on_stream_mismatch():
    """The PRIMARY must verify the puller's stream before acking: a
    cross-stream from_seq that lands in this ring would otherwise
    falsely release wait_replicated() for writes the standby is about
    to discard (advisor follow-up on the stream-id fix)."""
    log = ReplicationLog(sync_timeout_s=0.2)
    for i in range(3):
        log.append([{"op": "set", "path": f"/k{i}", "value": ""}])
    out = log.pull(
        from_seq=3, wait_s=0, puller_id="s", stream_id="other-ring"
    )
    assert out["snapshot_needed"] is True
    assert log.status()["acked_seq"] == 0  # nothing acked
    seq = log.append([{"op": "set", "path": "/k3", "value": ""}])
    assert log.wait_replicated(seq) is False
    # the SAME seq from the right stream acks normally
    out = log.pull(
        from_seq=3, wait_s=0, puller_id="s", stream_id=log.stream_id
    )
    assert "entries" in out
    assert log.status()["acked_seq"] == 2


@pytest.mark.slow
def test_ensemble_promotion_chain_no_replicated_write_lost(tmp_path):
    """The ensemble property over GENERATIONS: a 3-server ensemble
    (durable FileWal backends) survives a chain of primary deaths —
    each round writes, waits for full bounded-sync, hard-kills the
    primary (HTTP gone, backend abandoned un-closed = kill -9), and
    promotes a standby; every replicated write of every previous
    generation must be served by every new primary.  The dead
    ex-primary rejoins each round by reopening its WAL dir with
    --standby-of semantics (the stream-id check forces snapshot
    repair), and epochs stay strictly monotonic through all three
    promotions."""
    from dcos_commons_tpu.storage.file_persister import FileWalPersister

    def boot(name, standby_of=""):
        return StateServer(
            FileWalPersister(str(tmp_path / name)),
            replicate_from=standby_of,
        ).start()

    servers = {"a": boot("a")}
    servers["b"] = boot("b", servers["a"].url)
    servers["c"] = boot("c", servers["a"].url)
    primary = "a"
    expect = {}
    last_epoch = 1
    try:
        for gen, nxt in enumerate(["b", "c", "a"]):
            client = RemotePersister(servers[primary].url)
            for i in range(5):
                key = f"/svc/g{gen}k{i}"
                value = f"v{gen}.{i}".encode()
                client.set(key, value)
                expect[key] = value

            def synced():
                st = RemotePersister(
                    servers[primary].url
                )._call("/v1/repl/status", {})
                return (
                    st["standby_count"] == 2
                    and not st["standby_lagging"]
                    and st["acked_seq"] == st["seq"]
                )

            wait_until(synced, timeout_s=30, what=f"gen {gen} full sync")
            # primary dies hard: HTTP torn down, backend NOT closed
            dead = primary
            servers[dead]._server.shutdown()
            servers[dead]._server.server_close()
            out = RemotePersister(
                servers[nxt].url
            )._call("/v1/repl/promote", {})
            assert out["epoch"] > last_epoch, (gen, out)
            last_epoch = out["epoch"]
            primary = nxt
            promoted = RemotePersister(servers[primary].url)
            for key, value in expect.items():
                assert promoted.get(key) == value, (gen, key)
            # survivors re-point at the new primary; the dead one
            # rejoins by reopening its OWN WAL dir as a fresh standby
            for name in servers:
                if name == primary:
                    continue
                if name != dead:
                    servers[name].stop()
                servers[name] = boot(name, servers[primary].url)
    finally:
        for server in servers.values():
            try:
                server.stop()
            except OSError:
                pass  # the hard-killed generation's socket is gone


def test_repl_status_cli_verb(capsys):
    """`state-server --repl-status URL` prints the monitoring JSON an
    operator alerts on (role, epoch, seq, per-standby map) and exits
    0; an unreachable server is an error, not a traceback."""
    from dcos_commons_tpu.storage.remote import main as state_server_main

    primary = StateServer(MemPersister()).start()
    standby = StateServer(MemPersister(), replicate_from=primary.url).start()
    try:
        RemotePersister(primary.url).set("/k", b"v")
        wait_until(
            lambda: RemotePersister(primary.url)._call(
                "/v1/repl/status", {}
            )["standby_attached"],
            what="standby attach",
        )
        assert state_server_main(["--repl-status", primary.url]) == 0
        out = json.loads(capsys.readouterr().out)
        assert out["role"] == "primary"
        assert out["standby_count"] == 1
        assert out["seq"] >= 1
        assert len(out["standbys"]) == 1
    finally:
        standby.stop()
        primary.stop()
    assert state_server_main(["--repl-status", primary.url]) == 1
    assert "repl-status failed" in capsys.readouterr().err
    # a hand-typed scheme-less URL: error message, never a traceback
    assert state_server_main(["--repl-status", "host:1234"]) == 1
    assert "repl-status failed" in capsys.readouterr().err
