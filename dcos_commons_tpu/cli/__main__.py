"""``python -m dcos_commons_tpu.cli`` entry point.

Reference: sdk/cli/main.go:1-12 — the 12-line default CLI binary every
framework ships.
"""

import sys

from dcos_commons_tpu.cli.commands import main

if __name__ == "__main__":
    sys.exit(main())
