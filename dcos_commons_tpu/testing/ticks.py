"""The Send/Expect tick vocabulary.

Reference: sdk/testing/.../SimulationTick.java:6 (marker interface),
Send* (SendOffer/SendTaskStatus builders) and the Expect catalogue
(Expect.java:47-631: declinedLastOffer, launchedTasks, taskKilled,
planStatus, stepStatus, recoveryStep, storedTaskEnv, samePod, ...).
Send ticks mutate the world then run one scheduler cycle (one pass of
the offer thread); Expect ticks assert and never advance the clock.
"""

from __future__ import annotations

from typing import Optional

from dcos_commons_tpu.common import TaskState, TaskStatus
from dcos_commons_tpu.offer.inventory import TpuHost
from dcos_commons_tpu.plan.status import Status
from dcos_commons_tpu.testing.runner import SimulationWorld


class SimulationTick:
    def apply(self, world: SimulationWorld) -> None:
        raise NotImplementedError

    def describe(self) -> str:
        return type(self).__name__


class Send(SimulationTick):
    """Mutation tick: subclasses mutate, then one cycle runs."""

    def mutate(self, world: SimulationWorld) -> None:
        raise NotImplementedError

    def apply(self, world: SimulationWorld) -> None:
        self.mutate(world)
        world.scheduler.run_cycle()


class Expect(SimulationTick):
    """Assertion tick."""


# ---------------------------------------------------------------------------
# Send ticks
# ---------------------------------------------------------------------------


class AdvanceCycles(Send):
    """Run N scheduler cycles with no other stimulus (the reference's
    equivalent is sending an empty offer cycle)."""

    def __init__(self, n: int = 1):
        self.n = n

    def mutate(self, world: SimulationWorld) -> None:
        for _ in range(self.n - 1):
            world.scheduler.run_cycle()

    def describe(self) -> str:
        return f"AdvanceCycles({self.n})"


class SendStatus(Send):
    """Inject a TaskStatus for a task *name* (the current launch's id
    is resolved from the agent, or pass task_id explicitly).
    Reference: SendTaskStatus (SimulationTick)."""

    def __init__(
        self,
        task_name: str,
        state: TaskState,
        ready: bool = False,
        message: str = "",
        task_id: Optional[str] = None,
    ):
        self.task_name = task_name
        self.state = state
        self.ready = ready
        self.message = message
        self.task_id = task_id

    def mutate(self, world: SimulationWorld) -> None:
        task_id = self.task_id or world.agent.task_id_of(self.task_name)
        assert task_id is not None, f"no launch recorded for {self.task_name}"
        info = world.agent.task_info_of(self.task_name)
        world.agent.send(
            TaskStatus(
                task_id=task_id,
                state=self.state,
                ready=self.ready,
                message=self.message,
                agent_id=info.agent_id if info else "",
            )
        )

    def describe(self) -> str:
        return f"SendStatus({self.task_name}, {self.state.value})"


class SendTaskRunning(SendStatus):
    def __init__(self, task_name: str, ready: bool = True):
        super().__init__(task_name, TaskState.RUNNING, ready=ready)


class SendTaskFinished(SendStatus):
    def __init__(self, task_name: str):
        super().__init__(task_name, TaskState.FINISHED)


class SendTaskFailed(SendStatus):
    def __init__(self, task_name: str, message: str = "simulated crash"):
        super().__init__(task_name, TaskState.FAILED, message=message)


class AddHost(Send):
    def __init__(self, host: TpuHost):
        self.host = host

    def mutate(self, world: SimulationWorld) -> None:
        world.inventory.add_host(self.host)


class RemoveHost(Send):
    def __init__(self, host_id: str):
        self.host_id = host_id

    def mutate(self, world: SimulationWorld) -> None:
        world.inventory.remove_host(self.host_id)


class MarkHostDown(Send):
    def __init__(self, host_id: str):
        self.host_id = host_id

    def mutate(self, world: SimulationWorld) -> None:
        world.inventory.mark_down(self.host_id)


class MarkHostUp(Send):
    def __init__(self, host_id: str):
        self.host_id = host_id

    def mutate(self, world: SimulationWorld) -> None:
        world.inventory.mark_up(self.host_id)


class PreemptHost(Send):
    """TPU preemption (ISSUE 13): the host's task processes die
    silently, then the scheduler is told (the operator verb / agent
    plane path) — tasks are stamped PERMANENTLY_FAILED and LOST, and
    a gang member's loss synthesizes the gang recovery plan."""

    def __init__(self, host_id: str):
        self.host_id = host_id

    def mutate(self, world: SimulationWorld) -> None:
        fail = getattr(world.agent, "fail_host", None)
        if callable(fail):
            fail(self.host_id)
        world.scheduler.preempt_host(self.host_id)

    def describe(self) -> str:
        return f"PreemptHost({self.host_id})"


class DrainHost(Send):
    """Maintenance drain: placement excludes the host immediately,
    serve backends surface draining, running work keeps running."""

    def __init__(self, host_id: str, window_s: float = 0.0):
        self.host_id = host_id
        self.window_s = window_s

    def mutate(self, world: SimulationWorld) -> None:
        world.scheduler.drain_host(self.host_id, window_s=self.window_s)

    def describe(self) -> str:
        return f"DrainHost({self.host_id})"


class HostUp(Send):
    """Clear preempted/maintenance/down marks (the `up` verb)."""

    def __init__(self, host_id: str):
        self.host_id = host_id

    def mutate(self, world: SimulationWorld) -> None:
        world.scheduler.undrain_host(self.host_id)

    def describe(self) -> str:
        return f"HostUp({self.host_id})"


class _PlanVerb(Send):
    """Plan lifecycle verbs (reference: PlansQueries.java:47-231)."""

    def __init__(self, plan_name: str, phase: Optional[str] = None,
                 step: Optional[str] = None):
        self.plan_name = plan_name
        self.phase = phase
        self.step = step

    def _target(self, world: SimulationWorld):
        plan = world.scheduler.plan(self.plan_name)
        assert plan is not None, f"no plan {self.plan_name}"
        if self.phase is None:
            return plan
        phase = plan.phase(self.phase)
        assert phase is not None, f"no phase {self.phase}"
        if self.step is None:
            return phase
        step = phase.step(self.step) if hasattr(phase, "step") else None
        if step is None:
            for s in phase.steps:
                if s.name == self.step:
                    step = s
        assert step is not None, f"no step {self.step}"
        return step

    def describe(self) -> str:
        return f"{type(self).__name__}({self.plan_name})"


class PlanInterrupt(_PlanVerb):
    def mutate(self, world: SimulationWorld) -> None:
        self._target(world).interrupt()


class PlanContinue(_PlanVerb):
    def mutate(self, world: SimulationWorld) -> None:
        self._target(world).proceed()


class PlanRestart(_PlanVerb):
    def mutate(self, world: SimulationWorld) -> None:
        self._target(world).restart()


class PlanForceComplete(_PlanVerb):
    def mutate(self, world: SimulationWorld) -> None:
        self._target(world).force_complete()


class PlanStart(_PlanVerb):
    """Kick an interrupted sidecar plan: restart + proceed, matching
    the HTTP verb (reference: PlansQueries.start)."""

    def mutate(self, world: SimulationWorld) -> None:
        target = self._target(world)
        target.restart()
        target.proceed()


# ---------------------------------------------------------------------------
# Expect ticks
# ---------------------------------------------------------------------------


class ExpectLaunchedTasks(Expect):
    """The launches since the last ExpectLaunchedTasks/ExpectNoLaunches
    are exactly these task names (reference: Expect.launchedTasks)."""

    def __init__(self, *task_names: str):
        self.task_names = set(task_names)

    def apply(self, world: SimulationWorld) -> None:
        new = world.new_launches()
        names = {i.name for i in new}
        assert names == self.task_names, (
            f"expected launches {sorted(self.task_names)}, got {sorted(names)}"
        )
        world.launch_watermark = len(world.agent.launched)

    def describe(self) -> str:
        return f"ExpectLaunchedTasks({sorted(self.task_names)})"


class ExpectNoLaunches(Expect):
    def apply(self, world: SimulationWorld) -> None:
        new = world.new_launches()
        assert not new, f"unexpected launches: {[i.name for i in new]}"


class ExpectTaskKilled(Expect):
    def __init__(self, task_name: str):
        self.task_name = task_name

    def apply(self, world: SimulationWorld) -> None:
        from dcos_commons_tpu.common import task_name_of

        new = world.new_kills()
        names = set()
        for task_id in new:
            try:
                names.add(task_name_of(task_id))
            except ValueError:
                pass
        assert self.task_name in names, (
            f"expected kill of {self.task_name}, kills={sorted(names)}"
        )
        world.kill_watermark = len(world.agent.kills)

    def describe(self) -> str:
        return f"ExpectTaskKilled({self.task_name})"


class ExpectTaskNotKilled(Expect):
    def __init__(self, task_name: str):
        self.task_name = task_name

    def apply(self, world: SimulationWorld) -> None:
        assert self.task_name not in world.agent.killed_names(), (
            f"{self.task_name} was killed"
        )


class ExpectPlanStatus(Expect):
    def __init__(self, plan_name: str, status: Status):
        self.plan_name = plan_name
        self.status = status

    def apply(self, world: SimulationWorld) -> None:
        plan = world.scheduler.plan(self.plan_name)
        assert plan is not None, f"no plan {self.plan_name}"
        actual = plan.get_status()
        assert actual is self.status, (
            f"plan {self.plan_name}: expected {self.status.value}, "
            f"got {actual.value}"
        )

    def describe(self) -> str:
        return f"ExpectPlanStatus({self.plan_name}={self.status.value})"


class ExpectStepStatus(Expect):
    def __init__(self, plan_name: str, phase_name: str, step_name: str,
                 status: Status):
        self.plan_name = plan_name
        self.phase_name = phase_name
        self.step_name = step_name
        self.status = status

    def apply(self, world: SimulationWorld) -> None:
        plan = world.scheduler.plan(self.plan_name)
        assert plan is not None, f"no plan {self.plan_name}"
        step = plan.step(self.phase_name, self.step_name)
        assert step is not None, (
            f"no step {self.phase_name}/{self.step_name} in {self.plan_name}"
        )
        actual = step.get_status()
        assert actual is self.status, (
            f"step {self.step_name}: expected {self.status.value}, "
            f"got {actual.value}"
        )

    def describe(self) -> str:
        return (
            f"ExpectStepStatus({self.plan_name}/{self.phase_name}/"
            f"{self.step_name}={self.status.value})"
        )


class ExpectDeploymentComplete(Expect):
    def apply(self, world: SimulationWorld) -> None:
        plan = world.scheduler.deploy_manager.get_plan()
        assert plan.is_complete, (
            f"deploy plan is {plan.get_status().value}"
        )


class ExpectAllPlansComplete(Expect):
    def apply(self, world: SimulationWorld) -> None:
        for name, plan in world.scheduler.plans().items():
            assert plan.is_complete, f"plan {name} is {plan.get_status().value}"


class ExpectRecoveryStep(Expect):
    """The recovery plan currently contains a step covering this pod
    instance (reference: Expect.recoveryStep)."""

    def __init__(self, asset: str, present: bool = True):
        self.asset = asset
        self.present = present

    def apply(self, world: SimulationWorld) -> None:
        plan = world.scheduler.recovery_manager.get_plan()
        assets = set()
        for step in plan.all_steps():
            assets |= step.get_asset_names()
        if self.present:
            assert self.asset in assets, (
                f"no recovery step for {self.asset}; recovery assets={assets}"
            )
        else:
            assert self.asset not in assets, (
                f"unexpected recovery step for {self.asset}"
            )

    def describe(self) -> str:
        return f"ExpectRecoveryStep({self.asset}, present={self.present})"


class ExpectTaskEnv(Expect):
    """The stored/launched TaskInfo for a task carries this env var
    (reference: Expect.storedTaskEnv)."""

    def __init__(self, task_name: str, key: str, value: Optional[str] = None):
        self.task_name = task_name
        self.key = key
        self.value = value

    def apply(self, world: SimulationWorld) -> None:
        info = world.agent.task_info_of(self.task_name)
        if info is None:
            info = world.state_store.fetch_task(self.task_name)
        assert info is not None, f"no TaskInfo for {self.task_name}"
        assert self.key in info.env, (
            f"{self.task_name} env lacks {self.key}; keys={sorted(info.env)}"
        )
        if self.value is not None:
            assert info.env[self.key] == self.value, (
                f"{self.task_name} env[{self.key}]={info.env[self.key]!r}, "
                f"expected {self.value!r}"
            )

    def describe(self) -> str:
        return f"ExpectTaskEnv({self.task_name}, {self.key})"


class ExpectTaskStateStored(Expect):
    def __init__(self, task_name: str, state: TaskState):
        self.task_name = task_name
        self.state = state

    def apply(self, world: SimulationWorld) -> None:
        status = world.state_store.fetch_status(self.task_name)
        assert status is not None, f"no status for {self.task_name}"
        assert status.state is self.state, (
            f"{self.task_name}: stored {status.state.value}, "
            f"expected {self.state.value}"
        )

    def describe(self) -> str:
        return f"ExpectTaskStateStored({self.task_name}={self.state.value})"


class ExpectReservationCount(Expect):
    def __init__(self, count: int):
        self.count = count

    def apply(self, world: SimulationWorld) -> None:
        actual = len(world.scheduler.ledger.all())
        assert actual == self.count, (
            f"expected {self.count} reservations, ledger has {actual}"
        )

    def describe(self) -> str:
        return f"ExpectReservationCount({self.count})"


class ExpectDistinctHosts(Expect):
    """Placement assertion: these tasks landed on pairwise-distinct
    hosts (reference: Expect.samePod inverse)."""

    def __init__(self, *task_names: str):
        self.task_names = task_names

    def apply(self, world: SimulationWorld) -> None:
        hosts = []
        for name in self.task_names:
            info = world.agent.task_info_of(name)
            assert info is not None, f"no launch for {name}"
            hosts.append(info.agent_id)
        assert len(set(hosts)) == len(hosts), (
            f"expected distinct hosts, got {dict(zip(self.task_names, hosts))}"
        )


class ExpectSameHost(Expect):
    def __init__(self, *task_names: str):
        self.task_names = task_names

    def apply(self, world: SimulationWorld) -> None:
        hosts = set()
        for name in self.task_names:
            info = world.agent.task_info_of(name)
            assert info is not None, f"no launch for {name}"
            hosts.add(info.agent_id)
        assert len(hosts) == 1, (
            f"expected colocated tasks, hosts={hosts}"
        )


class ExpectDeclined(Expect):
    """The last evaluated requirement failed to place (reference:
    Expect.declinedLastOffer) — asserted via the offer outcome
    tracker's most recent record."""

    def __init__(self, requirement_fragment: str = ""):
        self.fragment = requirement_fragment

    def apply(self, world: SimulationWorld) -> None:
        records = world.scheduler.outcome_tracker.to_json()
        assert records, "no offer evaluations recorded"
        last = records[-1]
        assert not last["passed"], (
            f"last evaluation passed: {last['requirement']}"
        )
        if self.fragment:
            assert self.fragment in last["requirement"], (
                f"last declined requirement {last['requirement']!r} does not "
                f"match {self.fragment!r}"
            )
