"""The fleet model: TPU hosts, chips, torus coordinates, snapshots.

Replaces Mesos agents + offers (reference: offer/MesosResourcePool.java
— the consumable view of one offer — and the agent attributes consumed
by placement rules).  The scheduler owns this inventory and synthesizes
"offers" (ResourceSnapshots) from it each cycle, instead of waiting
for a Mesos master to send them.

Torus model: each physical TPU pod ("slice") is a grid of hosts; each
host owns a contiguous block of chips (e.g. a v5e host owns a 2x2
block; an 8x8-host pod is a 16x16 chip torus).  Chip coordinates are
global within the slice, so ICI adjacency between two hosts is
checkable from their host-grid coordinates alone.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple


@dataclass(frozen=True)
class TpuHost:
    """One TPU VM worker host.

    ``slice_id`` names the physical pod this host belongs to;
    ``grid`` is the host's (x, y) coordinate in that pod's host grid;
    ``chip_block`` is the (w, h) block of chips the host owns.
    CPU-only hosts (the helloworld case) have ``chip_block == (0, 0)``.
    """

    host_id: str
    hostname: str = ""
    slice_id: str = ""
    generation: str = ""             # "" for CPU-only hosts
    grid: Tuple[int, int] = (0, 0)
    chip_block: Tuple[int, int] = (0, 0)
    cpus: float = 8.0
    memory_mb: int = 16384
    disk_mb: int = 102400
    ports: Tuple[Tuple[int, int], ...] = ((10000, 12000),)
    attributes: Dict[str, str] = field(default_factory=dict)
    zone: str = ""
    region: str = ""

    def __post_init__(self) -> None:
        if not self.hostname:
            object.__setattr__(self, "hostname", self.host_id)

    @property
    def chips_per_host(self) -> int:
        return self.chip_block[0] * self.chip_block[1]

    def chip_ids(self) -> List[str]:
        """Global chip ids "slice/x,y" for every chip this host owns.

        Memoized: the dataclass is frozen, so the id list is a pure
        function of the host — snapshot synthesis used to re-format
        these strings for every host on every cycle."""
        cached = self.__dict__.get("_chip_ids")
        if cached is None:
            w, h = self.chip_block
            ox, oy = self.grid[0] * w, self.grid[1] * h
            cached = tuple(
                f"{self.slice_id}/{ox + dx},{oy + dy}"
                for dy in range(h)
                for dx in range(w)
            )
            object.__setattr__(self, "_chip_ids", cached)
        return list(cached)


class ResourceSnapshot:
    """A consumable view of one host's free resources — the offer.

    Reference: offer/MesosResourcePool.java.  Mutated by evaluation
    stages as they claim resources; commit/rollback is handled by the
    evaluator working on copies (gang evaluation is all-or-nothing).

    Copy-on-write contract (fleet-scale fast path): the inventory's
    per-view caches hand out ``shared`` masters that are reused across
    requirements WITHOUT copying; reading them is free, but a caller
    that wants to consume must ``copy()`` first — the mutators raise
    on a shared snapshot so a forgotten clone fails loudly instead of
    silently poisoning every later evaluation.
    """

    def __init__(
        self,
        host: TpuHost,
        cpus: float,
        memory_mb: int,
        disk_mb: int,
        free_chips: Set[str],
        used_ports: Set[int],
    ):
        self.host = host
        self.cpus = cpus
        self.memory_mb = memory_mb
        self.disk_mb = disk_mb
        self.free_chips = set(free_chips)
        self.used_ports = set(used_ports)
        self.shared = False

    def copy(self) -> "ResourceSnapshot":
        return ResourceSnapshot(
            self.host, self.cpus, self.memory_mb, self.disk_mb,
            set(self.free_chips), set(self.used_ports),
        )

    def _writable(self) -> None:
        if self.shared:
            raise RuntimeError(
                f"shared snapshot for {self.host.host_id!r}: copy() "
                "before mutating (copy-on-write contract)"
            )

    # -- consumption (evaluation stages call these) -------------------

    def try_consume_scalar(self, cpus: float, memory_mb: int, disk_mb: int) -> bool:
        self._writable()
        if self.cpus + 1e-9 < cpus or self.memory_mb < memory_mb \
                or self.disk_mb < disk_mb:
            return False
        self.cpus -= cpus
        self.memory_mb -= memory_mb
        self.disk_mb -= disk_mb
        return True

    def try_consume_chips(self, count: int) -> Optional[List[str]]:
        self._writable()
        if len(self.free_chips) < count:
            return None
        taken = sorted(self.free_chips)[:count]
        self.free_chips -= set(taken)
        return taken

    def allocate_port(self, requested: int = 0) -> Optional[int]:
        """Fixed port if requested, else next free dynamic port."""
        self._writable()
        if requested:
            if requested in self.used_ports:
                return None
            self.used_ports.add(requested)
            return requested
        for lo, hi in self.host.ports:
            for port in range(lo, hi):
                if port not in self.used_ports:
                    self.used_ports.add(port)
                    return port
        return None


def host_field(host: TpuHost, field_name: str) -> str:
    """The ONE host-field accessor shared by placement rules and the
    inverted indexes — a rule and the index it pre-filters through
    must read the same value or candidates silently diverge."""
    if field_name == "hostname":
        return host.hostname
    if field_name == "zone":
        return host.zone
    if field_name == "region":
        return host.region
    if field_name == "generation":
        return host.generation
    if field_name == "slice":
        return host.slice_id
    return host.attributes.get(field_name, "")


class _ViewCache:
    """Per-(inventory, ledger-view) snapshot cache — the dirty-host
    incremental evaluation state.  One exists per view OBJECT, so a
    multi-service scheduler alternating between its merged view and a
    bare ledger no longer thrashes a single shared cache."""

    __slots__ = (
        "snaps", "tokens", "gen_token", "topo_gen", "ordered",
        "order_gen", "free_chip_count", "fully_free_by_slice",
    )

    def __init__(self) -> None:
        self.snaps: Dict[str, ResourceSnapshot] = {}  # host_id -> shared master
        self.tokens: Dict[str, object] = {}           # host_id -> per-host token
        self.gen_token: object = None                 # view token at last sync
        self.topo_gen: int = -1
        self.ordered: Optional[List[ResourceSnapshot]] = None
        self.order_gen: int = -1                      # suspect-order stamp
        # ledger-dependent placement indexes, maintained with the
        # snapshots they describe (a stale index would pre-filter
        # against a fleet that no longer exists)
        self.free_chip_count: Dict[str, int] = {}
        self.fully_free_by_slice: Dict[str, Set[str]] = {}


class HostIndex:
    """Read-only index facade handed to placement pre-filtering: the
    inventory's inverted field indexes (topology-keyed) plus one
    view's chip-availability indexes (ledger-keyed).  Rules emit
    candidate host-id SETS through this instead of filtering one
    snapshot at a time."""

    def __init__(self, inventory: "SliceInventory", cache: _ViewCache):
        self._inventory = inventory
        self._cache = cache

    def universe(self) -> Set[str]:
        """All up host ids (callers must not mutate)."""
        return self._inventory._up_ids()

    def hosts_with(self, field_name: str, value: str) -> Set[str]:
        return self._inventory._field_index(field_name).get(value, _EMPTY)

    def value_index(self, field_name: str) -> Dict[str, Set[str]]:
        """value -> up host ids for one field (callers must not mutate)."""
        return self._inventory._field_index(field_name)

    def ordinal(self, host_id: str) -> int:
        """Host's position in snapshot iteration order — candidates
        sorted by this reproduce exactly the full-scan winner."""
        return self._inventory._ordinals().get(host_id, 1 << 30)

    def snapshot(self, host_id: str) -> Optional[ResourceSnapshot]:
        return self._cache.snaps.get(host_id)

    def ordered_snapshots(self) -> List[ResourceSnapshot]:
        return self._inventory._ordered_snapshots(self._cache)

    def snapshots_for(self, host_ids: Set[str]) -> List[ResourceSnapshot]:
        """Shared snapshots for a candidate set, in scan order."""
        up = self._inventory._up_ids()
        if host_ids is up or len(host_ids) >= len(up):
            # candidate sets are built from up-host indexes, so a
            # full-cardinality set IS the universe — reuse the cached
            # scan-order list instead of re-sorting the whole fleet
            # per instance
            return self.ordered_snapshots()
        ordinals = self._inventory._ordinals()
        snaps = self._cache.snaps
        return [
            snaps[h]
            for h in sorted(host_ids, key=lambda h: ordinals.get(h, 1 << 30))
            if h in snaps
        ]

    def hosts_with_free_chips(self, count: int) -> Set[str]:
        """Up hosts with at least ``count`` chips unreserved under
        this view (free-chip-count bucket query)."""
        if count <= 0:
            return self.universe()
        return {
            h for h, n in self._cache.free_chip_count.items() if n >= count
        }

    def rule_candidates(self, rule, ctx) -> Optional[Set[str]]:
        """A rule's candidate host-id set, memoized per topology
        generation when the rule declares a static
        ``candidate_key()`` (field matches and their and/or algebra
        — incl. the O(fleet) inverted-match universe subtraction).
        The PR 9 remainder: a multi-instance deploy used to pay the
        full set algebra once PER INSTANCE; now it pays one lookup.
        Dynamic rules (count-dependent) fall through to a fresh
        computation every call — membership correctness first."""
        key_of = getattr(rule, "candidate_key", None)
        key = key_of() if callable(key_of) else None
        if key is None:
            return rule.candidate_host_ids(ctx, self)
        inv = self._inventory
        topo = inv._topology_gen
        entry = inv._static_candidates.get(key)
        if entry is not None and entry[0] == topo:
            inv.static_cand_hits += 1
            return entry[1]
        inv.static_cand_misses += 1
        cand = rule.candidate_host_ids(ctx, self)
        if len(inv._static_candidates) >= 256:
            # distinct static rules are few (they come from pod
            # specs); a runaway vocabulary resets rather than grows
            inv._static_candidates.clear()
        inv._static_candidates[key] = (
            topo, frozenset(cand) if cand is not None else None
        )
        return cand

    def fully_free_by_slice(self) -> Dict[str, Set[str]]:
        """slice_id -> hosts whose entire chip block is unreserved —
        the torus-neighborhood pre-filter (gang placement requires
        fully-free hosts, offer/torus.py check())."""
        return self._cache.fully_free_by_slice



_EMPTY: Set[str] = frozenset()  # type: ignore[assignment]


class SliceInventory:
    """The fleet: hosts + the reservation ledger's committed claims.

    ``snapshots()`` synthesizes the current "offers": per-host free
    resources after subtracting every committed reservation.  This is
    the L0-replacement — where the reference waits for resourceOffers
    callbacks (FrameworkScheduler.java:196), our scheduler scans this.

    Fleet-scale fast path: snapshots are cached PER VIEW and synced
    incrementally — each pass asks the view which hosts changed since
    the last sync (``changed_hosts_since``) and rebuilds exactly
    those, so an idle 10k-host fleet pays an O(1) token compare, not
    10k rebuild-or-copy decisions.  ``offer_view`` returns SHARED
    copy-on-write masters; ``snapshots`` keeps the legacy
    copy-per-host contract for direct callers."""

    def __init__(self, hosts: Optional[List[TpuHost]] = None):
        self._hosts: Dict[str, TpuHost] = {}
        self._down: Set[str] = set()
        # TPU-native failure-domain states (ISSUE 13): ``preempted``
        # hosts are DOWN with a cause (the cloud took the capacity
        # back; tasks there are dead and recovery treats them as
        # PERMANENT); ``maintenance`` hosts are UP but drain-first —
        # excluded from every snapshot/candidate set (no NEW
        # placements, fresh or in-place growth) while their running
        # work keeps running until the operator (or the maintenance
        # automation) kills it.  Values: host_id -> wall-clock window
        # end (0.0 = indefinite / unknown).
        self._preempted: Set[str] = set()
        self._maintenance: Dict[str, float] = {}
        # per-view snapshot caches: id(view) -> (view, _ViewCache).
        # The view object itself is held (not just its id()): id reuse
        # after GC must never validate a stale cache.
        self._view_caches: Dict[int, tuple] = {}
        # static placement candidate sets (HostIndex.rule_candidates):
        # candidate_key -> (topology_gen, frozenset | None).  Stamped
        # per entry, so no invalidation hook is needed — a topology
        # bump simply makes every stamp compare stale.
        self._static_candidates: Dict[tuple, tuple] = {}
        self.static_cand_hits = 0
        self.static_cand_misses = 0
        self.cache_hits = 0
        self.cache_misses = 0
        # dirty-host count of the most recent sync that found work
        # (surfaced as the offers.dirty_hosts gauge)
        self.last_dirty_hosts = 0
        # bumped on any EFFECTIVE host add/remove/up/down so per-cycle
        # consumers know when to rebuild; per-host change generations
        # let view caches compute exactly which hosts moved
        self._topology_gen = 0
        self._host_topo_gen: Dict[str, int] = {}
        # inverted indexes over UP hosts (field value -> host ids),
        # built lazily per field and discarded on topology change;
        # _ordinal_cache maps host_id -> scan position
        self._field_indexes: Dict[str, Dict[str, Set[str]]] = {}
        self._index_gen = -1
        self._ordinal_cache: Dict[str, int] = {}
        self._ordinal_gen: object = -1
        # soft placement signal (health plane): suspect hosts sort
        # LAST in scan order — superset-sound, a suspect host is still
        # offered, it just loses first-fit ties to healthy peers.
        # Order changes bump _order_gen so scan-order caches (ordinals,
        # per-view ordered lists) re-sort without touching snapshots.
        self._suspect: frozenset = frozenset()
        self._suspect_sources: Dict[str, frozenset] = {}
        self._order_gen = 0
        self._scan_cache: Optional[List[TpuHost]] = None
        self._scan_cache_gen: object = None
        self._up_ids_cache: Optional[Set[str]] = None
        self._up_ids_gen = -1
        self._hosts_by_id: Optional[Dict[str, TpuHost]] = None
        self._hosts_by_id_gen = -1
        for host in hosts or []:
            self.add_host(host)

    @property
    def topology_generation(self) -> int:
        return self._topology_gen

    # -- mutators (the ONLY writers of host state; each effective
    # change bumps the generation so caches and indexes re-sync) ------

    def add_host(self, host: TpuHost) -> None:
        self._hosts[host.host_id] = host
        self._topology_gen += 1
        self._host_topo_gen[host.host_id] = self._topology_gen

    def remove_host(self, host_id: str) -> None:
        if host_id not in self._hosts:
            return  # no-op: an unknown host must not dirty the fleet
        self._hosts.pop(host_id, None)
        self._down.discard(host_id)
        self._preempted.discard(host_id)
        self._maintenance.pop(host_id, None)
        self._topology_gen += 1
        self._host_topo_gen[host_id] = self._topology_gen
        # journal compaction: removed hosts' stamps must outlive every
        # view cache that hasn't observed the removal yet, so they are
        # kept — but a months-long churny fleet must not accumulate
        # them without bound.  Past 2x the live fleet, drop non-member
        # stamps and clear the view caches outright: a from-scratch
        # resync can never miss a pruned removal.
        if len(self._host_topo_gen) > 2 * max(len(self._hosts), 512):
            self._host_topo_gen = {
                h: g for h, g in self._host_topo_gen.items()
                if h in self._hosts
            }
            self._view_caches.clear()

    def mark_down(self, host_id: str) -> None:
        """Host lost/maintenance: excluded from snapshots (the TASK_LOST
        / PARTITION_AWARE analogue, SURVEY.md section 5.3)."""
        if host_id in self._hosts and host_id not in self._down:
            self._down.add(host_id)
            self._topology_gen += 1
            self._host_topo_gen[host_id] = self._topology_gen

    def mark_up(self, host_id: str) -> None:
        # no-op guard: re-marking an up (or unknown) host used to bump
        # the generation anyway, invalidating every per-cycle hosts
        # dict and dirtying the whole fleet for nothing.  A returning
        # host sheds its preemption mark (the capacity is back) but
        # NOT a maintenance mark — the drain was scheduled by an
        # operator and only clear_host_state/the window may end it.
        if host_id in self._down:
            self._down.discard(host_id)
            self._preempted.discard(host_id)
            self._topology_gen += 1
            self._host_topo_gen[host_id] = self._topology_gen

    # -- preemption / maintenance (ISSUE 13) --------------------------

    def set_preempted(self, host_id: str) -> bool:
        """Immediate, involuntary capacity loss: the host is DOWN (its
        tasks are dead, snapshots excluded) and the preemption cause is
        recorded so recovery and the /v1/hosts surface can tell a
        preemption from a plain heartbeat loss.  Returns False when
        the host is unknown or already marked."""
        if host_id not in self._hosts or host_id in self._preempted:
            return False
        self._preempted.add(host_id)
        self._maintenance.pop(host_id, None)
        if host_id not in self._down:
            self._down.add(host_id)
        self._topology_gen += 1
        self._host_topo_gen[host_id] = self._topology_gen
        return True

    def set_maintenance(self, host_id: str, window_end: float = 0.0) -> bool:
        """Scheduled drain: the host stays UP (running work keeps
        running, in-place relaunches of existing footprints still
        work) but is HARD-excluded from snapshots and candidate
        indexes — no new placement lands on a host about to go away.
        ``window_end`` is the wall-clock end of the maintenance window
        (0.0 = indefinite); the elastic-resize decision rule reads it
        to choose waiting over shrinking.  Returns False when the
        host is unknown or already draining with the same window."""
        if host_id not in self._hosts:
            return False
        if self._maintenance.get(host_id) == window_end and \
                host_id in self._maintenance:
            return False
        self._maintenance[host_id] = float(window_end)
        self._topology_gen += 1
        self._host_topo_gen[host_id] = self._topology_gen
        return True

    def clear_host_state(self, host_id: str) -> bool:
        """Operator ``up`` verb: shed preempted/maintenance/down marks
        and return the host to full placement eligibility."""
        if host_id not in self._hosts:
            return False
        changed = (
            host_id in self._down
            or host_id in self._preempted
            or host_id in self._maintenance
        )
        if not changed:
            return False
        self._down.discard(host_id)
        self._preempted.discard(host_id)
        self._maintenance.pop(host_id, None)
        self._topology_gen += 1
        self._host_topo_gen[host_id] = self._topology_gen
        return True

    def host_state(self, host_id: str) -> str:
        """One of "up" | "down" | "preempted" | "maintenance" ("" for
        an unknown host).  ``maintenance`` wins over up (the host IS
        up — that is the point of a drain)."""
        if host_id not in self._hosts:
            return ""
        if host_id in self._preempted:
            return "preempted"
        if host_id in self._down:
            return "down"
        if host_id in self._maintenance:
            return "maintenance"
        return "up"

    def maintenance_window(self, host_id: str) -> Optional[float]:
        """Window end for a draining host (0.0 = indefinite), None
        when the host is not in maintenance."""
        return self._maintenance.get(host_id)

    def maintenance_hosts(self) -> Dict[str, float]:
        return dict(self._maintenance)

    def preempted_hosts(self) -> Set[str]:
        return set(self._preempted)

    def host_states(self) -> Dict[str, dict]:
        """Per-host state rows for GET /v1/hosts (operator surface)."""
        out: Dict[str, dict] = {}
        for host_id, host in self._hosts.items():
            row: Dict[str, object] = {
                "state": self.host_state(host_id),
                "slice": host.slice_id,
                "chips": host.chips_per_host,
            }
            window = self._maintenance.get(host_id)
            if window is not None:
                row["window_end"] = window
            out[host_id] = row
        return out

    def _placement_excluded(self, host_id: str) -> bool:
        """Down OR draining: no snapshot, no candidate membership."""
        return host_id in self._down or host_id in self._maintenance

    # -- queries ------------------------------------------------------

    def is_up(self, host_id: str) -> bool:
        return host_id in self._hosts and host_id not in self._down

    def host(self, host_id: str) -> Optional[TpuHost]:
        return self._hosts.get(host_id)

    def hosts(self) -> List[TpuHost]:
        return list(self._hosts.values())

    def up_hosts(self) -> List[TpuHost]:
        return [h for h in self._hosts.values() if h.host_id not in self._down]

    def hosts_by_id(self) -> Dict[str, TpuHost]:
        """host_id -> host over the WHOLE fleet (incl. down hosts),
        cached on the topology generation.  Callers must not mutate —
        every evaluation context of a cycle shares this dict."""
        gen = self._topology_gen
        if self._hosts_by_id is None or self._hosts_by_id_gen != gen:
            self._hosts_by_id = dict(self._hosts)
            self._hosts_by_id_gen = gen
        return self._hosts_by_id

    # -- snapshots ----------------------------------------------------

    def snapshots(self, ledger: "ReservationLedgerView") -> List[ResourceSnapshot]:
        """Legacy contract: synthesize the current offers as MUTABLE
        per-host copies.  Direct callers (tests, tools) may consume
        them freely; the evaluator's fast path uses ``offer_view``."""
        cache = self._sync_view(ledger)
        return [s.copy() for s in self._ordered_snapshots(cache)]

    def offer_view(self, ledger: "ReservationLedgerView") -> HostIndex:
        """Sync this view's cache against the ledger + topology and
        return the index facade over SHARED copy-on-write snapshots.
        This is the per-requirement entry point: an unchanged fleet
        costs one token compare, a changed one costs O(dirty hosts)."""
        return HostIndex(self, self._sync_view(ledger))

    def debug_stats(self) -> Dict[str, object]:
        """Dirty-set / cache / index observability for
        /v1/debug/offers (the slow-cycle triage surface).  Runs on
        HTTP threads while the cycle thread mutates: the C-level
        list()/dict() snapshots below are atomic under the GIL, so
        iteration can never see a resize mid-flight."""
        caches = list(self._view_caches.values())
        field_indexes = dict(self._field_indexes)
        return {
            "topology_generation": self._topology_gen,
            "hosts": len(self._hosts),
            "up_hosts": len(self._up_ids()),
            "preempted_hosts": sorted(self._preempted),
            "maintenance_hosts": dict(sorted(self._maintenance.items())),
            "suspect_hosts": sorted(self._suspect),
            "last_dirty_hosts": self.last_dirty_hosts,
            "snapshot_cache": {
                "hits": self.cache_hits,
                "misses": self.cache_misses,
                "views": len(caches),
                "entries": sum(len(c.snaps) for _, c in caches),
            },
            "index_cardinalities": {
                f: len(ix) for f, ix in field_indexes.items()
            },
            "static_candidates": {
                "hits": self.static_cand_hits,
                "misses": self.static_cand_misses,
                "entries": len(self._static_candidates),
            },
        }

    # -- incremental sync (internal) ----------------------------------

    # distinct live views are few (a service's ledger, the multi
    # merged view); the bound only matters when views are RECREATED —
    # live options updates swap the evaluator's ledger object, and
    # each superseded view would otherwise pin a fleet-sized snapshot
    # cache forever
    _MAX_VIEW_CACHES = 8

    def _sync_view(self, view: "ReservationLedgerView") -> _ViewCache:
        key = id(view)
        entry = self._view_caches.pop(key, None)
        if entry is None or entry[0] is not view:
            cache = _ViewCache()
            while len(self._view_caches) >= self._MAX_VIEW_CACHES:
                # LRU eviction: every sync re-inserts at the end, so
                # the first key is the least-recently-synced view
                self._view_caches.pop(next(iter(self._view_caches)))
            self._view_caches[key] = (view, cache)
        else:
            # re-insert at the end (most-recently used)
            self._view_caches[key] = entry
            cache = entry[1]
        token_fn = getattr(view, "generation_token", None)
        token = token_fn() if token_fn is not None else None
        if (
            token is not None
            and cache.gen_token == token
            and cache.topo_gen == self._topology_gen
        ):
            # steady state: nothing changed anywhere — O(1)
            self.cache_hits += len(cache.snaps)
            self.last_dirty_hosts = 0
            return cache
        # which hosts moved?  Ledger side from the view's change
        # journal (None = unknown -> per-host token compare), topology
        # side from the per-host generation stamps.
        changed: Optional[Set[str]] = None
        if cache.gen_token is not None:
            changed_fn = getattr(view, "changed_hosts_since", None)
            if changed_fn is not None:
                changed = changed_fn(cache.gen_token)
        if cache.topo_gen != self._topology_gen and changed is not None:
            changed = set(changed) | {
                h for h, g in self._host_topo_gen.items()
                if g > cache.topo_gen
            }
        if changed is None:
            self._sync_full(view, cache)
        else:
            self._sync_dirty(view, cache, changed)
        cache.gen_token = token
        cache.topo_gen = self._topology_gen
        return cache

    def _sync_dirty(
        self, view: "ReservationLedgerView", cache: _ViewCache,
        dirty: Set[str],
    ) -> None:
        self.last_dirty_hosts = len(dirty)
        if not dirty:
            self.cache_hits += len(cache.snaps)
            return
        gen_of = getattr(view, "host_generation", None)
        rebuilt = 0
        for host_id in dirty:
            host = self._hosts.get(host_id)
            if host is None or self._placement_excluded(host_id):
                self._drop_entry(cache, host_id)
                continue
            token = gen_of(host_id) if gen_of is not None else None
            self._rebuild_entry(view, cache, host, token)
            rebuilt += 1
        self.cache_misses += rebuilt
        self.cache_hits += len(cache.snaps) - rebuilt

    def _sync_full(
        self, view: "ReservationLedgerView", cache: _ViewCache
    ) -> None:
        """No change journal available: fall back to comparing every
        up host's per-view token (the PR-1 path, minus the copies)."""
        gen_of = getattr(view, "host_generation", None)
        seen: Set[str] = set()
        rebuilt = 0
        for host in self._hosts.values():
            host_id = host.host_id
            if self._placement_excluded(host_id):
                self._drop_entry(cache, host_id)
                continue
            seen.add(host_id)
            token = gen_of(host_id) if gen_of is not None else None
            current = cache.snaps.get(host_id)
            if (
                token is not None
                and current is not None
                and current.host is host
                and cache.tokens.get(host_id) == token
            ):
                self.cache_hits += 1
                continue
            self.cache_misses += 1
            rebuilt += 1
            self._rebuild_entry(view, cache, host, token)
        for host_id in list(cache.snaps):
            if host_id not in seen:
                self._drop_entry(cache, host_id)
        self.last_dirty_hosts = rebuilt

    def _rebuild_entry(
        self, view, cache: _ViewCache, host: TpuHost, token
    ) -> None:
        snap = self._build_snapshot(host, view)
        snap.shared = True
        host_id = host.host_id
        prev = cache.snaps.get(host_id)
        if prev is not None and prev.host.slice_id != host.slice_id:
            # host re-registered under a different slice: it must
            # leave the OLD slice's fully-free bucket or the gang
            # pre-filter counts a host that is no longer there
            old_bucket = cache.fully_free_by_slice.get(prev.host.slice_id)
            if old_bucket is not None:
                old_bucket.discard(host_id)
        cache.snaps[host_id] = snap
        cache.tokens[host_id] = token
        cache.ordered = None
        n_free = len(snap.free_chips)
        cache.free_chip_count[host_id] = n_free
        bucket = cache.fully_free_by_slice.setdefault(host.slice_id, set())
        if host.chips_per_host and n_free == host.chips_per_host:
            bucket.add(host_id)
        else:
            bucket.discard(host_id)

    def _drop_entry(self, cache: _ViewCache, host_id: str) -> None:
        snap = cache.snaps.pop(host_id, None)
        cache.tokens.pop(host_id, None)
        cache.free_chip_count.pop(host_id, None)
        if snap is not None:
            cache.ordered = None
            bucket = cache.fully_free_by_slice.get(snap.host.slice_id)
            if bucket is not None:
                bucket.discard(host_id)

    def _ordered_snapshots(self, cache: _ViewCache) -> List[ResourceSnapshot]:
        if cache.ordered is None or cache.order_gen != self._order_gen:
            snaps = cache.snaps
            cache.ordered = [
                snaps[h.host_id]
                for h in self._scan_hosts()
                if h.host_id in snaps
            ]
            cache.order_gen = self._order_gen
        return cache.ordered

    # -- scan order (health plane's soft placement signal) ------------

    def set_suspect_hosts(self, host_ids, source: str = "") -> None:
        """Demote hosts to the END of placement scan order (the health
        monitor pushes its straggler suspect set here).  Superset-sound
        by construction: membership in every candidate set and
        snapshot cache is untouched — only iteration ORDER changes, so
        a suspect host still places when it is the only fit.

        ``source`` keys the contribution: on a SHARED multi-service
        inventory every service's monitor pushes only its own
        stragglers, so the effective demotion set is the UNION across
        sources — a service with no stragglers pushing ``set()`` must
        not clobber another service's demotion of a host they share.
        No-op when the union is unchanged (a per-source change that
        doesn't move the union never resorts); otherwise only the
        ordering caches re-sort (snapshot content is
        order-independent)."""
        new = frozenset(host_ids)
        if self._suspect_sources.get(source, frozenset()) == new:
            return
        if new:
            self._suspect_sources[source] = new
        else:
            self._suspect_sources.pop(source, None)
        union = frozenset().union(
            *self._suspect_sources.values()
        ) if self._suspect_sources else frozenset()
        if union == self._suspect:
            return
        self._suspect = union
        self._order_gen += 1

    def suspect_hosts(self) -> Set[str]:
        return set(self._suspect)

    def _scan_hosts(self) -> List[TpuHost]:
        """Hosts in scan (tie-breaking) order: registration order with
        suspect hosts moved to the back, cached until topology or the
        suspect set changes.  The ONE order shared by ``_ordinals`` and
        the per-view ordered snapshot lists — indexed candidates sorted
        by ordinal must reproduce exactly the full-scan winner."""
        gen = (self._topology_gen, self._order_gen)
        if self._scan_cache is None or self._scan_cache_gen != gen:
            if self._suspect:
                head = [
                    h for h in self._hosts.values()
                    if h.host_id not in self._suspect
                ]
                head += [
                    h for h in self._hosts.values()
                    if h.host_id in self._suspect
                ]
                self._scan_cache = head
            else:
                self._scan_cache = list(self._hosts.values())
            self._scan_cache_gen = gen
        return self._scan_cache

    # -- inverted indexes (internal; rebuilt on topology change) ------

    def _up_ids(self) -> Set[str]:
        # capture the generation BEFORE building: a topology mutation
        # racing this rebuild (HTTP debug thread vs cycle thread) must
        # leave the cache stamped stale, not mask the change until the
        # NEXT topology bump
        gen = self._topology_gen
        if self._up_ids_cache is None or self._up_ids_gen != gen:
            # C-level snapshots first: debug_stats calls this from
            # HTTP threads while the cycle thread mutates the fleet.
            # Maintenance hosts are excluded like down ones — this set
            # feeds candidate indexes, and a draining host may take no
            # new placements (its RUNNING work is untouched)
            excluded = set(self._down) | set(self._maintenance)
            self._up_ids_cache = {
                h for h in list(self._hosts) if h not in excluded
            }
            self._up_ids_gen = gen
        return self._up_ids_cache

    def _ordinals(self) -> Dict[str, int]:
        gen = (self._topology_gen, self._order_gen)
        if self._ordinal_gen != gen:
            self._ordinal_cache = {
                host.host_id: i
                for i, host in enumerate(self._scan_hosts())
            }
            self._ordinal_gen = gen
        return self._ordinal_cache

    def _field_index(self, field_name: str) -> Dict[str, Set[str]]:
        gen = self._topology_gen
        if self._index_gen != gen:
            self._field_indexes = {}
            self._index_gen = gen
        index = self._field_indexes.get(field_name)
        if index is None:
            index = {}
            for host in self._hosts.values():
                if self._placement_excluded(host.host_id):
                    continue
                index.setdefault(
                    host_field(host, field_name), set()
                ).add(host.host_id)
            self._field_indexes[field_name] = index
        return index

    def _build_snapshot(
        self, host: TpuHost, ledger: "ReservationLedgerView"
    ) -> ResourceSnapshot:
        free_chips = set(host.chip_ids())
        used_ports: Set[int] = set()
        cpus, mem, disk = host.cpus, host.memory_mb, host.disk_mb
        for res in ledger.reserved_on(host.host_id):
            cpus -= res.cpus
            mem -= res.memory_mb
            disk -= res.disk_mb
            free_chips -= set(res.chip_ids)
            used_ports |= set(res.ports)
        return ResourceSnapshot(host, cpus, mem, disk, free_chips, used_ports)


class ReservationLedgerView:
    """What SliceInventory needs from the ledger (breaks import cycle)."""

    def reserved_on(self, host_id: str):  # pragma: no cover - interface
        raise NotImplementedError

    def host_generation(self, host_id: str):
        """Change token for ``reserved_on(host_id)``; snapshots cached
        against it are reused while it compares equal.  None (the
        default) means "unknown — never cache"."""
        return None

    def generation_token(self):
        """Whole-view change token: snapshots synced against it are
        reused wholesale while it compares equal.  None (the default)
        means "unknown — re-check every host each pass"."""
        return None

    def changed_hosts_since(self, token):
        """Host ids whose ``reserved_on`` may differ from when the
        view reported ``token``; None (the default) means "unknown —
        treat every host as potentially dirty"."""
        return None


def make_test_fleet(
    slice_id: str = "pod-0",
    host_grid: Tuple[int, int] = (2, 2),
    chip_block: Tuple[int, int] = (2, 2),
    generation: str = "v5e",
    cpus: float = 16.0,
    memory_mb: int = 65536,
    zone_of=None,
) -> List[TpuHost]:
    """Fabricate a TPU pod's hosts (the SendOffer-builder equivalent,
    reference: sdk/testing Expect/SendOffer fixtures)."""
    hosts = []
    for gy in range(host_grid[1]):
        for gx in range(host_grid[0]):
            host_id = f"{slice_id}-h{gx}-{gy}"
            hosts.append(
                TpuHost(
                    host_id=host_id,
                    slice_id=slice_id,
                    generation=generation,
                    grid=(gx, gy),
                    chip_block=chip_block,
                    cpus=cpus,
                    memory_mb=memory_mb,
                    zone=zone_of(gx, gy) if zone_of else f"zone-{gx}",
                )
            )
    return hosts
