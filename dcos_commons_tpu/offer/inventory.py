"""The fleet model: TPU hosts, chips, torus coordinates, snapshots.

Replaces Mesos agents + offers (reference: offer/MesosResourcePool.java
— the consumable view of one offer — and the agent attributes consumed
by placement rules).  The scheduler owns this inventory and synthesizes
"offers" (ResourceSnapshots) from it each cycle, instead of waiting
for a Mesos master to send them.

Torus model: each physical TPU pod ("slice") is a grid of hosts; each
host owns a contiguous block of chips (e.g. a v5e host owns a 2x2
block; an 8x8-host pod is a 16x16 chip torus).  Chip coordinates are
global within the slice, so ICI adjacency between two hosts is
checkable from their host-grid coordinates alone.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple


@dataclass(frozen=True)
class TpuHost:
    """One TPU VM worker host.

    ``slice_id`` names the physical pod this host belongs to;
    ``grid`` is the host's (x, y) coordinate in that pod's host grid;
    ``chip_block`` is the (w, h) block of chips the host owns.
    CPU-only hosts (the helloworld case) have ``chip_block == (0, 0)``.
    """

    host_id: str
    hostname: str = ""
    slice_id: str = ""
    generation: str = ""             # "" for CPU-only hosts
    grid: Tuple[int, int] = (0, 0)
    chip_block: Tuple[int, int] = (0, 0)
    cpus: float = 8.0
    memory_mb: int = 16384
    disk_mb: int = 102400
    ports: Tuple[Tuple[int, int], ...] = ((10000, 12000),)
    attributes: Dict[str, str] = field(default_factory=dict)
    zone: str = ""
    region: str = ""

    def __post_init__(self) -> None:
        if not self.hostname:
            object.__setattr__(self, "hostname", self.host_id)

    @property
    def chips_per_host(self) -> int:
        return self.chip_block[0] * self.chip_block[1]

    def chip_ids(self) -> List[str]:
        """Global chip ids "slice/x,y" for every chip this host owns."""
        w, h = self.chip_block
        ox, oy = self.grid[0] * w, self.grid[1] * h
        return [
            f"{self.slice_id}/{ox + dx},{oy + dy}"
            for dy in range(h)
            for dx in range(w)
        ]


class ResourceSnapshot:
    """A consumable view of one host's free resources — the offer.

    Reference: offer/MesosResourcePool.java.  Mutated by evaluation
    stages as they claim resources; commit/rollback is handled by the
    evaluator working on copies (gang evaluation is all-or-nothing).
    """

    def __init__(
        self,
        host: TpuHost,
        cpus: float,
        memory_mb: int,
        disk_mb: int,
        free_chips: Set[str],
        used_ports: Set[int],
    ):
        self.host = host
        self.cpus = cpus
        self.memory_mb = memory_mb
        self.disk_mb = disk_mb
        self.free_chips = set(free_chips)
        self.used_ports = set(used_ports)

    def copy(self) -> "ResourceSnapshot":
        return ResourceSnapshot(
            self.host, self.cpus, self.memory_mb, self.disk_mb,
            set(self.free_chips), set(self.used_ports),
        )

    # -- consumption (evaluation stages call these) -------------------

    def try_consume_scalar(self, cpus: float, memory_mb: int, disk_mb: int) -> bool:
        if self.cpus + 1e-9 < cpus or self.memory_mb < memory_mb \
                or self.disk_mb < disk_mb:
            return False
        self.cpus -= cpus
        self.memory_mb -= memory_mb
        self.disk_mb -= disk_mb
        return True

    def try_consume_chips(self, count: int) -> Optional[List[str]]:
        if len(self.free_chips) < count:
            return None
        taken = sorted(self.free_chips)[:count]
        self.free_chips -= set(taken)
        return taken

    def allocate_port(self, requested: int = 0) -> Optional[int]:
        """Fixed port if requested, else next free dynamic port."""
        if requested:
            if requested in self.used_ports:
                return None
            self.used_ports.add(requested)
            return requested
        for lo, hi in self.host.ports:
            for port in range(lo, hi):
                if port not in self.used_ports:
                    self.used_ports.add(port)
                    return port
        return None


class SliceInventory:
    """The fleet: hosts + the reservation ledger's committed claims.

    ``snapshots()`` synthesizes the current "offers": per-host free
    resources after subtracting every committed reservation.  This is
    the L0-replacement — where the reference waits for resourceOffers
    callbacks (FrameworkScheduler.java:196), our scheduler scans this.
    """

    def __init__(self, hosts: Optional[List[TpuHost]] = None):
        self._hosts: Dict[str, TpuHost] = {}
        self._down: Set[str] = set()
        for host in hosts or []:
            self.add_host(host)

    def add_host(self, host: TpuHost) -> None:
        self._hosts[host.host_id] = host

    def remove_host(self, host_id: str) -> None:
        self._hosts.pop(host_id, None)
        self._down.discard(host_id)

    def mark_down(self, host_id: str) -> None:
        """Host lost/maintenance: excluded from snapshots (the TASK_LOST
        / PARTITION_AWARE analogue, SURVEY.md section 5.3)."""
        if host_id in self._hosts:
            self._down.add(host_id)

    def mark_up(self, host_id: str) -> None:
        self._down.discard(host_id)

    def is_up(self, host_id: str) -> bool:
        return host_id in self._hosts and host_id not in self._down

    def host(self, host_id: str) -> Optional[TpuHost]:
        return self._hosts.get(host_id)

    def hosts(self) -> List[TpuHost]:
        return list(self._hosts.values())

    def up_hosts(self) -> List[TpuHost]:
        return [h for h in self._hosts.values() if h.host_id not in self._down]

    def snapshots(self, ledger: "ReservationLedgerView") -> List[ResourceSnapshot]:
        out = []
        for host in self.up_hosts():
            reserved = ledger.reserved_on(host.host_id)
            free_chips = set(host.chip_ids())
            used_ports: Set[int] = set()
            cpus, mem, disk = host.cpus, host.memory_mb, host.disk_mb
            for res in reserved:
                cpus -= res.cpus
                mem -= res.memory_mb
                disk -= res.disk_mb
                free_chips -= set(res.chip_ids)
                used_ports |= set(res.ports)
            out.append(
                ResourceSnapshot(host, cpus, mem, disk, free_chips, used_ports)
            )
        return out


class ReservationLedgerView:
    """What SliceInventory needs from the ledger (breaks import cycle)."""

    def reserved_on(self, host_id: str):  # pragma: no cover - interface
        raise NotImplementedError


def make_test_fleet(
    slice_id: str = "pod-0",
    host_grid: Tuple[int, int] = (2, 2),
    chip_block: Tuple[int, int] = (2, 2),
    generation: str = "v5e",
    cpus: float = 16.0,
    memory_mb: int = 65536,
    zone_of=None,
) -> List[TpuHost]:
    """Fabricate a TPU pod's hosts (the SendOffer-builder equivalent,
    reference: sdk/testing Expect/SendOffer fixtures)."""
    hosts = []
    for gy in range(host_grid[1]):
        for gx in range(host_grid[0]):
            host_id = f"{slice_id}-h{gx}-{gy}"
            hosts.append(
                TpuHost(
                    host_id=host_id,
                    slice_id=slice_id,
                    generation=generation,
                    grid=(gx, gy),
                    chip_block=chip_block,
                    cpus=cpus,
                    memory_mb=memory_mb,
                    zone=zone_of(gx, gy) if zone_of else f"zone-{gx}",
                )
            )
    return hosts
