"""The closed health->action loop: SLO autoscaling + remediation.

ROADMAP item 2, the last anchor item: PR 10's detectors were
deliberately advisory and PR 13 shipped only the minimal
straggler->replace seam.  This module turns verdicts into ACTIONS —
and makes the actions themselves safe to automate:

  scale-out    a serving SLO breach episode (TTFT p95 / queue depth /
               KV-pages-free, the gauges each serve pod already
               exports) that persists past the hysteresis hold
               synthesizes a plan that raises the pod's instance
               count and deploys the new instances through the
               NORMAL offer cycle (WAL, reservations, discipline).
  scale-in     a sustained quiet-pod episode (the low-watermark
               ``QuietPodWatcher`` over the same gauges) synthesizes
               a decommission-shaped teardown riding the
               DecommissionPlanFactory's kill+unreserve+erase steps,
               with the /v1/endpoints surface flipping
               ``draining:true`` and a router drain-grace elapsing
               BEFORE the kill step fires.
  remediation  the PR 13 auto-replace seam, grown general: a
               confirmed straggler episode triggers at most one
               audited pod replace per episode, preferring gang
               members (whose whole slice the straggler drags) and —
               under the ``remediation`` policy gate — any pod on
               the suspect host.

Flap-proofing is structural, not best-effort:

  * hysteresis: a breach must HOLD for ``breach_hold_s`` (quiet for
    ``quiet_hold_s``) before any action; the quiet watermark sits at
    ``quiet_factor`` x the breach threshold, so a signal parked
    between the two bands never triggers anything in either
    direction (the band cannot oscillate on a constant signal).
  * per-direction cooldowns: after EVERY terminal plan state the
    direction's cooldown clock starts; no same-direction action
    fires inside it.
  * single flight: one action per pod at a time, no scale-down while
    a scale-up is in flight (and vice versa), no remediation while
    any scale plan for the service is active.  Bounded concurrent
    growth ACROSS services is the multi scheduler's existing
    OfferDiscipline: a scale-out plan makes the service "growing",
    so ``ParallelFootprintDiscipline`` bounds how many grow at once.
  * flap hold: while a lease-churn episode is open (flapping
    leadership), every automated action is suspended — a control
    plane trading its own lease must not also be resizing the fleet.

Every action RIDES THE PLAN ENGINE: one ``autoscale`` plan whose
phases are interruptible/resumable/force-completable through the
ordinary plan verbs, journaled as ``kind=health`` events
(trace-correlated to the triggering episode's task/signal/value),
and failover-safe — action latches and cooldown clocks are seeded
from the REPLAYED event journal exactly like ``LeaseChurnWatcher``,
so a successor neither re-fires a completed action nor forgets an
in-flight one (steps are idempotent and deployment steps re-seed
COMPLETE from the state store).

Layering invariant (enforced by the ``health-plan-only`` sdklint
rule): nothing in this module writes the ledger or state store
directly.  Mutation happens only through factory-built plan steps
(plan/builders.py, decommission/factory.py) and journaled scheduler
verbs (``set_pod_count``, ``restart_pod``).
"""

from __future__ import annotations

import math
import re
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from dcos_commons_tpu.plan.phase import Phase
from dcos_commons_tpu.plan.plan import Plan
from dcos_commons_tpu.plan.plan_manager import PlanManager
from dcos_commons_tpu.plan.step import ActionStep, Step
from dcos_commons_tpu.plan.strategy import ParallelStrategy, SerialStrategy

AUTOSCALE_PLAN_NAME = "autoscale"
# state-store property prefix for the durable desired-count override
# (written by the set_pod_count VERB, read back by SchedulerBuilder so
# a failover/restart rebuilds the deploy plan at the scaled width)
COUNT_PROPERTY_PREFIX = "autoscale-count-"


@dataclass(frozen=True)
class ActionPolicy:
    """Knobs of the automated loop.  Both action families default OFF
    — automated resizing/eviction is an operator decision."""

    autoscale: bool = False
    remediation: bool = False
    max_instances: int = 4
    # cap how many instances one scale-out action may add
    scale_step_max: int = 2
    # hysteresis holds: how long an episode must persist before acting
    breach_hold_s: float = 10.0
    quiet_hold_s: float = 60.0
    # the quiet low watermark sits at quiet_factor x the breach
    # threshold (QuietPodWatcher) — the dead band between the two
    # is what makes a constant signal flap-proof
    quiet_factor: float = 0.25
    # per-direction cooldowns, started at EVERY terminal plan state
    cooldown_out_s: float = 60.0
    cooldown_in_s: float = 300.0
    # router drain grace between the endpoints draining flip and the
    # scale-in kill step
    drain_grace_s: float = 5.0
    # drain-with-migration (serve/migration.py): at drain start, ask
    # the scale-in victim to MOVE its live sessions to the surviving
    # peers — the grace then covers router awareness only, not whole
    # generations, and the eventual kill cuts nothing off.  Best
    # effort: a pod without the migrate surface rides the grace
    # exactly as before
    drain_migrate: bool = True
    remediation_cooldown_s: float = 300.0


@dataclass(frozen=True)
class Decision:
    direction: str  # "out" | "in"
    target: int


def scale_out_target(
    count: int, max_instances: int, severity: float, step_max: int = 2
) -> int:
    """Target instance count for a breach of magnitude ``severity``
    (value/threshold; >= 1).  MONOTONE in severity by construction:
    the step is floor(log2(severity)) + 1, clamped to
    [1, step_max] — a 2x breach adds up to 2 instances, a marginal
    one adds 1 — and the target is clamped to ``max_instances``
    (hypothesis-tested in test_health_actions)."""
    sev = max(1.0, float(severity))
    step = max(1, min(int(step_max), int(math.floor(math.log2(sev))) + 1))
    return min(int(max_instances), int(count) + step)


def decide(
    now: float,
    *,
    policy: ActionPolicy,
    count: int,
    baseline: int,
    breach_since: Optional[float] = None,
    severity: float = 1.0,
    quiet_since: Optional[float] = None,
    active: Optional[str] = None,
    hold: bool = False,
    cooldown_out_until: float = 0.0,
    cooldown_in_until: float = 0.0,
) -> Optional[Decision]:
    """The PURE autoscale decision rule (the hypothesis properties in
    test_health_actions and the plancheck autoscale config both drive
    THIS function, not a transcription of it).

    Precedence: an open breach episode always dominates quiet (the
    two cannot emit opposite directions from one state); ``active``
    (an in-flight action on this pod) and ``hold`` (open lease-churn
    episode) suppress everything — the single-flight and flap-hold
    rules live here so every caller inherits them."""
    if not policy.autoscale or hold or active is not None:
        return None
    if breach_since is not None:
        if now - breach_since < policy.breach_hold_s:
            return None
        if now < cooldown_out_until:
            return None
        target = scale_out_target(
            count, policy.max_instances, severity, policy.scale_step_max
        )
        if target > count:
            return Decision("out", target)
        return None
    if quiet_since is not None and count > baseline:
        if now - quiet_since < policy.quiet_hold_s:
            return None
        if now < cooldown_in_until:
            return None
        return Decision("in", count - 1)
    return None


def remediation_allowed(
    now: float,
    *,
    enabled: bool,
    scale_active: bool,
    hold: bool,
    last_replace_t: Optional[float],
    cooldown_s: float,
) -> bool:
    """Gate for the auto-replace seam: never while a scale plan for
    the service is in flight (a remediation racing its own scale-out
    is exactly the storm the plancheck no-storm invariant forbids),
    never during a lease-churn flap hold, and rate-limited by its own
    cooldown so a detector wobble cannot evict pod after pod."""
    if not enabled or scale_active or hold:
        return False
    if last_replace_t is not None and now - last_replace_t < cooldown_s:
        return False
    return True


def seed_latches(
    events: List[dict],
) -> Tuple[Dict[str, dict], Dict[Tuple[str, str], float], Optional[float]]:
    """Fold replayed ``kind=health`` journal events into the
    governor's durable state: still-in-flight actions (a ``start``
    without a later terminal event), per-(pod, direction) last
    terminal times (the cooldown clocks), and the last auto-replace
    time.

    PERMUTATION-INVARIANT over the input list: events are folded in
    journal-sequence order (``seq``), so any shuffling of the same
    event set seeds identical latches — the property the failover
    contract needs and the hypothesis test pins."""
    in_flight: Dict[str, dict] = {}
    done_t: Dict[Tuple[str, str], float] = {}
    last_replace: Optional[float] = None
    for event in sorted(events, key=lambda e: e.get("seq", 0)):
        verb = event.get("verb")
        if verb in ("scale-out", "scale-in"):
            pod = str(event.get("pod", ""))
            direction = "out" if verb == "scale-out" else "in"
            stage = event.get("stage")
            if stage == "start":
                try:
                    in_flight[pod] = {
                        "direction": direction,
                        "from": int(event.get("from", 0)),
                        "to": int(event.get("to", 0)),
                        "t": float(event.get("t", 0.0)),
                    }
                except (TypeError, ValueError):
                    continue
            elif stage in ("complete", "abandoned"):
                in_flight.pop(pod, None)
                key = (pod, direction)
                done_t[key] = max(
                    done_t.get(key, 0.0), float(event.get("t", 0.0))
                )
        elif verb == "auto-replace":
            last_replace = max(
                last_replace or 0.0, float(event.get("t", 0.0))
            )
    return in_flight, done_t, last_replace


class ActionPlanManager(PlanManager):
    """Owns the dynamic ``autoscale`` plan: one phase per pod with an
    in-flight action (single flight makes "per pod" and "per action"
    the same thing), phases for different pods progressing in
    parallel.  Pruning is the engine's job (``_settle``) — a
    completed phase must be journaled and its cooldown clock started
    before it disappears."""

    def __init__(self):
        self._phases: Dict[str, Phase] = {}
        self._plan = Plan(AUTOSCALE_PLAN_NAME, [], ParallelStrategy())

    def get_plan(self) -> Plan:
        self._plan.phases = list(self._phases.values())
        return self._plan

    def get_candidates(self, dirty_assets: Set[str]) -> List[Step]:
        return self.get_plan().candidates(dirty_assets)

    def update(self, status) -> None:
        for phase in list(self._phases.values()):
            phase.update(status)

    def phase_for(self, pod_type: str) -> Optional[Phase]:
        return self._phases.get(pod_type)

    def add(self, pod_type: str, phase: Phase) -> None:
        self._phases[pod_type] = phase

    def remove(self, pod_type: str) -> None:
        self._phases.pop(pod_type, None)


class HealthActionEngine:
    """The governor: consumes detector episodes (via the
    HealthMonitor's watchers), applies :func:`decide`, synthesizes
    action phases, settles terminal ones, and carries the latches.

    Thread discipline: every entry point is called either from the
    cycle thread inside ``run_cycle`` (monitor.observe) or from an
    HTTP verb that holds the scheduler lock (``scale_pod``), so the
    engine itself needs no lock of its own."""

    def __init__(self, policy: Optional[ActionPolicy] = None,
                 clock=time.time):
        self.policy = policy or ActionPolicy()
        self.manager = ActionPlanManager()
        # pod type -> the YAML instance count (the scale-in floor);
        # set by the builder, defaulted lazily from the live spec
        self.baselines: Dict[str, int] = {}
        # launch backoff for scale-out deployment steps (set by the
        # builder alongside baselines): a crash-looping scale-out
        # instance must back off exactly like a deploy-plan instance,
        # not hot-retry every cycle.  None = DisabledBackoff.
        self.backoff = None
        self._clock = clock
        self._seeded = False
        # (pod, direction) -> last terminal time (cooldown clocks)
        self._done_t: Dict[Tuple[str, str], float] = {}
        self._last_replace_t: Optional[float] = None
        # hosts already remediated this episode (cleared event re-arms)
        self._replaced_hosts: Set[str] = set()
        self.actions_started = 0

    # -- failover seeding --------------------------------------------

    def seed(self, scheduler) -> None:
        """Replay the journal's ``kind=health`` events once per
        incarnation: cooldown clocks resume, and a still-in-flight
        action's plan is RE-SYNTHESIZED — its steps are idempotent
        and its deployment steps seed COMPLETE from the state store,
        so a successor resumes exactly where the deposed leader
        stopped instead of re-firing or forgetting."""
        if self._seeded:
            return
        self._seeded = True
        in_flight, self._done_t, self._last_replace_t = seed_latches(
            scheduler.journal.events(kinds=("health",))
        )
        from dcos_commons_tpu.specification.specs import SpecError

        for pod_type, latch in in_flight.items():
            try:
                pod = scheduler.spec.pod(pod_type)
            except SpecError:
                continue  # pod dropped from the spec since the event
            if self.manager.phase_for(pod_type) is not None:
                continue
            if latch["direction"] == "out":
                self._synthesize_out(
                    scheduler, pod, latch["from"], latch["to"]
                )
            else:
                self._synthesize_in(
                    scheduler, pod, latch["from"], latch["to"]
                )

    def _baseline(self, scheduler, pod_type: str) -> int:
        if pod_type not in self.baselines:
            from dcos_commons_tpu.specification.specs import SpecError

            try:
                self.baselines[pod_type] = scheduler.spec.pod(
                    pod_type
                ).count
            except SpecError:
                self.baselines[pod_type] = 1
        return self.baselines[pod_type]

    # -- the per-observe pass ----------------------------------------

    def observe(self, scheduler, monitor,
                now: Optional[float] = None) -> List[dict]:
        """One governor pass, called by HealthMonitor after the
        detectors scored.  Returns the journaled action events (the
        engine appends them itself — they are alerts and deserve the
        monitor's inline flush)."""
        now = self._clock() if now is None else now
        self.seed(scheduler)
        events = self._settle(scheduler, now)
        if not self.policy.autoscale:
            return events
        hold = bool(getattr(monitor.lease_churn, "alerted", False))
        for pod in scheduler.spec.pods:
            if pod.gang:
                # a gang pod's count is its mesh width, not a replica
                # count — gang serving scales by adding services, and
                # elastic re-slicing (recovery/elastic.py) owns width
                continue
            signal = self._pod_signal(scheduler, pod, monitor)
            if signal is None:
                continue
            breach_since, severity, quiet_since, trigger = signal
            active_phase = self.manager.phase_for(pod.type)
            active = (
                getattr(active_phase, "autoscale_direction", "out")
                if active_phase is not None else None
            )
            baseline = self._baseline(scheduler, pod.type)
            decision = decide(
                now,
                policy=self.policy,
                count=pod.count,
                baseline=baseline,
                breach_since=breach_since,
                severity=severity,
                quiet_since=quiet_since,
                active=active,
                hold=hold,
                cooldown_out_until=self._cooldown_until(pod.type, "out"),
                cooldown_in_until=self._cooldown_until(pod.type, "in"),
            )
            if decision is not None:
                events.append(self._start(
                    scheduler, pod, decision, now, trigger
                ))
        return events

    def _cooldown_until(self, pod_type: str, direction: str) -> float:
        done = self._done_t.get((pod_type, direction))
        if done is None:
            return 0.0
        window = (
            self.policy.cooldown_out_s if direction == "out"
            else self.policy.cooldown_in_s
        )
        return done + window

    @staticmethod
    def _task_owner(spec, task_name: str):
        """(pod_type, index) owning ``task_name`` by LONGEST-type
        match — a bare ``^<type>-(\\d+)-`` prefix test would hand pod
        ``web`` the tasks of a sibling pod named ``web-2`` (task
        names embed the type, and types may themselves end in a
        numeric segment)."""
        best = None
        for p in spec.pods:
            match = re.match(
                rf"^{re.escape(p.type)}-(\d+)-", task_name
            )
            if match and (best is None or len(p.type) > len(best[0])):
                best = (p.type, int(match.group(1)))
        return best

    def _pod_signal(self, scheduler, pod, monitor):
        """(breach_since, severity, quiet_since, trigger attrs) for
        one pod off the watcher state, or None when no serving task
        of the pod has ever reported (non-serving pods never
        autoscale).  Quiet requires EVERY live instance quiet — a
        pod with one idle and one loaded instance is load-imbalanced,
        not over-provisioned."""
        spec = scheduler.spec
        breach_since: Optional[float] = None
        severity = 1.0
        trigger: dict = {}
        for (task, sig), since in sorted(
            getattr(monitor.slo, "breach_since", {}).items()
        ):
            owner = self._task_owner(spec, task)
            if owner is None or owner[0] != pod.type:
                continue
            mag = monitor.slo.breach_severity.get((task, sig), 1.0)
            if breach_since is None or since < breach_since:
                breach_since = since
            if mag >= severity:
                severity = mag
                trigger = {
                    "task": task, "signal": sig,
                    "value": monitor.slo.breaches.get((task, sig)),
                }
        quiet_since: Optional[float] = None
        owned = {
            task: owner[1]
            for task in monitor.serving_stats
            for owner in [self._task_owner(spec, task)]
            if owner is not None and owner[0] == pod.type
        }
        if not owned and breach_since is None:
            return None
        if breach_since is None and owned:
            quiet = monitor.quiet.quiet_since
            if set(range(pod.count)) <= set(owned.values()) and all(
                t in quiet for t in owned
            ):
                quiet_since = max(quiet[t] for t in owned)
        return breach_since, severity, quiet_since, trigger

    # -- starting actions --------------------------------------------

    def _start(self, scheduler, pod, decision: Decision, now: float,
               trigger: dict) -> dict:
        from_count = pod.count
        if decision.direction == "out":
            self._synthesize_out(
                scheduler, pod, from_count, decision.target
            )
        else:
            self._synthesize_in(
                scheduler, pod, from_count, decision.target
            )
        self.actions_started += 1
        verb = "scale-out" if decision.direction == "out" else "scale-in"
        event = scheduler.journal.append(
            "health",
            verb=verb,
            stage="start",
            pod=pod.type,
            to=decision.target,
            t=now,
            message=(
                f"{verb} {pod.type}: {from_count} -> {decision.target} "
                + ("(SLO breach episode)" if decision.direction == "out"
                   else "(sustained quiet episode)")
            ),
            **{"from": from_count},
            **{k: v for k, v in trigger.items() if v is not None},
        )
        scheduler.metrics.incr(f"health.actions.{verb}")
        scheduler.nudge()  # the new plan work is pending NOW
        return event

    def request_scale(self, scheduler, pod_type: str,
                      target: int) -> Phase:
        """Operator-initiated scale (POST /v1/pod/<type>/scale):
        rides the exact same plan machinery — and the same
        single-flight rule — as the automated loop, skipping only the
        hysteresis holds (the operator IS the hysteresis).  Caller
        holds the scheduler lock.

        Settles terminal phases FIRST: with the health plane disabled
        (NullHealthMonitor) nothing else ever calls _settle, and a
        completed-but-unsettled phase would hold the single-flight
        latch against every future manual scale forever."""
        self.seed(scheduler)
        self._settle(scheduler, self._clock())
        pod = scheduler.spec.pod(pod_type)
        if pod.gang:
            raise ValueError(
                f"pod {pod_type!r} is a gang (count is its mesh "
                "width); elastic re-slicing owns gang width"
            )
        target = int(target)
        if target < 1:
            raise ValueError("count must be >= 1")
        baseline = self._baseline(scheduler, pod_type)
        if target < baseline:
            # the persisted-count overlay clamps to the YAML count on
            # every rebuild, so a below-floor scale would silently
            # undo itself at the next restart — refuse loudly instead
            raise ValueError(
                f"count {target} is below the YAML floor {baseline}; "
                "lower the pod's count in the service spec "
                "(allow-decommission) to shrink past it"
            )
        if self.manager.phase_for(pod_type) is not None:
            raise RuntimeError(
                f"a scale action for {pod_type!r} is already in "
                "flight (single-flight; interrupt it via the "
                "autoscale plan verbs first)"
            )
        if target == pod.count:
            raise ValueError(f"{pod_type} already has {target} instance(s)")
        now = self._clock()
        direction = "out" if target > pod.count else "in"
        if direction == "in" and target != pod.count - 1:
            # scale-in steps one instance at a time (highest index
            # first, the decommission discipline); repeat to go lower
            raise ValueError(
                f"scale-in proceeds one instance at a time "
                f"(ask for {pod.count - 1})"
            )
        self._start(
            scheduler, pod, Decision(direction, target), now,
            {"source": "operator"},
        )
        return self.manager.phase_for(pod_type)

    # -- plan synthesis ----------------------------------------------

    def _target_config_id(self, scheduler) -> str:
        store = getattr(scheduler, "config_store", None)
        if store is not None:
            target = store.get_target_config()
            if target:
                return target
        return getattr(scheduler.evaluator, "target_config_id", "")

    def _synthesize_out(self, scheduler, pod, from_count: int,
                        to_count: int) -> Phase:
        """grow (count verb) -> one deployment step per new instance,
        serial.  Idempotent for the failover re-synthesis: the grow
        verb no-ops at the target count and deployment steps seed
        COMPLETE from the state store for already-launched
        instances."""
        import dataclasses

        from dcos_commons_tpu.plan.builders import build_instance_steps

        pod_type = pod.type

        def grow(s) -> bool:
            s.set_pod_count(pod_type, to_count, source="autoscale")
            return True

        scaled = dataclasses.replace(pod, count=to_count)
        steps: List[Step] = [
            ActionStep(f"grow-{pod_type}-to-{to_count}", grow)
        ]
        steps += build_instance_steps(
            scaled,
            list(range(from_count, to_count)),
            scheduler.state_store,
            self._target_config_id(scheduler),
            backoff=self.backoff,
        )
        phase = Phase(
            f"scale-out-{pod_type}-{to_count}", steps, SerialStrategy()
        )
        phase.autoscale_direction = "out"
        phase.pod_type = pod_type
        phase.from_count = from_count
        phase.to_count = to_count
        self.manager.add(pod_type, phase)
        return phase

    def _synthesize_in(self, scheduler, pod, from_count: int,
                       to_count: int) -> Phase:
        """shrink (count verb) -> drain grace -> the decommission
        factory's kill+unreserve+erase, serial.  The shrink runs
        FIRST so the recovery scan stops owning the victim before
        anything dies; the phase's ``decommission_targets`` flips the
        victim's /v1/endpoints rows to ``draining:true`` from the
        moment the phase exists, and the drain step holds the kill
        until the router grace elapsed.  Across a failover the drain
        clock restarts from zero — conservative, never shorter."""
        from dcos_commons_tpu.decommission.factory import (
            build_scale_in_phase,
        )

        pod_type = pod.type

        def shrink(s) -> bool:
            s.set_pod_count(pod_type, to_count, source="autoscale")
            return True

        drain_started: List[float] = []
        victim_index = from_count - 1

        def drain(s) -> bool:
            if not drain_started:
                drain_started.append(self._clock())
                if self.policy.drain_migrate:
                    # move the victim's live sessions to surviving
                    # peers NOW, so the grace below covers router
                    # awareness — not whole generations — and the
                    # kill step cuts nothing off (serve/migration.py)
                    self._migrate_victim_sessions(
                        s, pod_type, victim_index, to_count
                    )
                return False
            return (
                self._clock() - drain_started[0]
                >= self.policy.drain_grace_s
            )

        phase = build_scale_in_phase(
            pod, from_count - 1,
            shrink_action=shrink,
            drain_action=drain,
            to_count=to_count,
        )
        phase.autoscale_direction = "in"
        phase.pod_type = pod_type
        phase.from_count = from_count
        phase.to_count = to_count
        self.manager.add(pod_type, phase)
        return phase

    def _migrate_victim_sessions(
        self, scheduler, pod_type: str, victim_index: int,
        to_count: int,
    ) -> None:
        """Best-effort drain-with-migration: POST the victim's serve
        worker a one-shot drain verb naming the SURVIVING instances
        as destinations (frameworks/jax serve_worker /migrate).  Any
        failure — no serving stats, no dialable peers, a pod built
        before the migrate surface — leaves the legacy wait-out drain
        in charge; this never blocks or fails the scale-in plan."""
        import json as _json
        import urllib.request

        try:
            serving = self._serving_addresses(scheduler, pod_type)
            victim = serving.get(victim_index)
            dests = {
                f"{pod_type}-{idx}": addr
                for idx, addr in serving.items()
                if idx < to_count
            }
            if victim is None or not dests:
                return
            req = urllib.request.Request(
                f"http://{victim}/migrate",
                data=_json.dumps(
                    {"verb": "drain", "dests": dests}
                ).encode("utf-8"),
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=120.0) as resp:
                report = _json.loads(resp.read().decode("utf-8"))
            rows = report.get("report") or []
            moved = sum(1 for r in rows if r.get("ok"))
            scheduler.journal.append(
                "health",
                verb="scale-in",
                stage="migrate",
                pod=pod_type,
                message=(
                    f"scale-in drain migrated {moved}/{len(rows)} "
                    f"live session(s) off {pod_type}-{victim_index}"
                ),
            )
        except Exception as e:  # noqa: BLE001 — best-effort by contract
            try:
                scheduler.journal.append(
                    "health",
                    verb="scale-in",
                    stage="migrate",
                    pod=pod_type,
                    message=(
                        f"scale-in drain of {pod_type}-{victim_index} "
                        f"fell back to wait-out: {e}"
                    ),
                )
            except Exception:  # noqa: BLE001, sdklint: disable=swallowed-exception — journaling a fallback must not break the drain step
                pass

    def _serving_addresses(
        self, scheduler, pod_type: str
    ) -> Dict[int, str]:
        """pod index -> dialable address for every instance of
        ``pod_type`` whose sandbox mirrors serving stats with an
        http_port annotation (the same advertised-port contract
        /v1/endpoints reads)."""
        reader = getattr(scheduler.agent, "serving_stats_of", None)
        if not callable(reader):
            return {}
        hosts = {
            h.host_id: h for h in scheduler.inventory.hosts()
        }
        out: Dict[int, str] = {}
        for info in scheduler.state_store.fetch_tasks():
            if info.pod_type != pod_type:
                continue
            try:
                stats = reader(info.name)
            except OSError:
                continue
            port = (stats or {}).get("http_port")
            if not port:
                continue
            host = hosts.get(info.agent_id)
            hostname = host.hostname if host else "127.0.0.1"
            out[info.pod_index] = f"{hostname}:{int(port)}"
        return out

    # -- settling ----------------------------------------------------

    def _settle(self, scheduler, now: float) -> List[dict]:
        """Journal terminal phases and start their cooldown clocks.
        EVERY terminal state counts — natural completion, operator
        force-complete — per the no-flap contract (the cooldown is
        what stands between a wobbling signal and an action storm).
        Errored/interrupted phases stay put for the operator (plan
        verbs are the exits); the single-flight rule holds while they
        do."""
        out: List[dict] = []
        for pod_type, phase in list(self.manager._phases.items()):
            if not phase.is_complete:
                continue
            direction = getattr(phase, "autoscale_direction", "out")
            self._done_t[(pod_type, direction)] = now
            self.manager.remove(pod_type)
            verb = "scale-out" if direction == "out" else "scale-in"
            event = scheduler.journal.append(
                "health",
                verb=verb,
                stage="complete",
                pod=pod_type,
                to=getattr(phase, "to_count", None),
                t=now,
                message=(
                    f"{verb} {pod_type} complete at "
                    f"{getattr(phase, 'to_count', '?')} instance(s); "
                    f"{direction}-cooldown started"
                ),
                **{"from": getattr(phase, "from_count", None)},
            )
            scheduler.metrics.incr(f"health.actions.{verb}_complete")
            out.append(event)
        return out

    def abandon(self, scheduler, pod_type: str) -> bool:
        """Operator bail-out (DELETE semantics): drop an in-flight
        action's phase without completing it.  Journaled as
        ``abandoned`` — which is a terminal state, so the cooldown
        clock starts (an operator abandoning a flap must not re-arm
        it instantly).  The persisted count is RECONCILED to deployed
        reality (the longest contiguous instance prefix that actually
        exists within the action's [from, to] range): an abandoned
        half-deployed scale-out must not leave a wider count behind
        that the next restart's overlay would silently resume, and an
        abandoned scale-in whose victim still runs takes the victim
        back into the spec."""
        # settle first (mirrors request_scale): with the health plane
        # disabled a COMPLETED phase must settle as complete, never
        # be "abandoned" with a false journal stage
        self._settle(scheduler, self._clock())
        phase = self.manager.phase_for(pod_type)
        if phase is None:
            return False
        now = self._clock()
        direction = getattr(phase, "autoscale_direction", "out")
        self._done_t[(pod_type, direction)] = now
        self.manager.remove(pod_type)
        from_count = getattr(phase, "from_count", None)
        to_count = getattr(phase, "to_count", None)
        settled_count = None
        if from_count is not None and to_count is not None:
            from dcos_commons_tpu.specification.specs import (
                SpecError,
                task_full_name,
            )

            try:
                pod = scheduler.spec.pod(pod_type)
            except SpecError:
                pod = None
            if pod is not None:
                lo = min(from_count, to_count)
                hi = max(from_count, to_count)
                settled_count = lo
                for index in range(lo, hi):
                    if any(
                        scheduler.state_store.fetch_task(
                            task_full_name(pod_type, index, t.name)
                        ) is not None
                        for t in pod.tasks
                    ):
                        settled_count = index + 1
                    else:
                        break
                scheduler.set_pod_count(
                    pod_type, settled_count, source="autoscale"
                )
        verb = "scale-out" if direction == "out" else "scale-in"
        scheduler.journal.append(
            "health", verb=verb, stage="abandoned", pod=pod_type,
            to=to_count, t=now, settled=settled_count,
            message=f"{verb} {pod_type} abandoned by operator"
            + (f" (count settled at {settled_count})"
               if settled_count is not None else ""),
        )
        scheduler.nudge()
        return True

    # -- remediation (the grown PR 13 seam) ---------------------------

    def remediate(self, scheduler, events: List[dict],
                  enabled: bool,
                  now: Optional[float] = None,
                  hold: bool = False) -> List[dict]:
        """Act on this pass's straggler episode edges: at most ONE
        audited replace per pass, per-host episode latch re-armed by
        the episode's cleared event, suppressed entirely while any
        scale plan is active or leadership is flapping.  Gang members
        are preferred (the straggler drags its whole slice); under
        the ``remediation`` policy gate any pod instance on the host
        qualifies.  The replace rides ``restart_pod(replace=True)``
        -> the recovery plan — operator-interruptible like every
        plan, and the re-place prefers non-suspect hosts because
        suspects sort last in placement scan order."""
        now = self._clock() if now is None else now
        for event in events:
            if event.get("detector") == "straggler" and \
                    event.get("cleared"):
                self._replaced_hosts.discard(event.get("host"))
        # the flap hold is the caller-passed STATEFUL episode flag
        # (monitor.lease_churn.alerted) — the churn alert event fires
        # only on the episode's opening edge, so an events-only check
        # would hold for exactly one pass of a multi-pass episode
        churn = hold or any(
            e.get("detector") == "lease-churn" and not e.get("cleared")
            for e in events
        )
        if not remediation_allowed(
            now,
            enabled=enabled,
            scale_active=bool(self.manager._phases),
            hold=churn,
            last_replace_t=self._last_replace_t,
            cooldown_s=self.policy.remediation_cooldown_s,
        ):
            return []
        out: List[dict] = []
        for event in events:
            if event.get("detector") != "straggler" or \
                    event.get("cleared"):
                continue
            host = event.get("host")
            if host in self._replaced_hosts:
                continue
            target = self._pod_on(scheduler, host)
            if target is None:
                continue
            pod_type, index = target
            # latch AFTER the replace succeeds: a transient store
            # error inside restart_pod must not consume the episode's
            # one allowed action with neither a replace nor an audit
            killed = scheduler.restart_pod(pod_type, index, replace=True)
            self._replaced_hosts.add(host)
            self._last_replace_t = now
            action = {
                "kind": "health",
                "verb": "auto-replace",
                "host": host,
                "pod": f"{pod_type}-{index}",
                "tasks": len(killed),
                "t": now,
                "message": (
                    f"auto-replace: confirmed straggler {host} carries "
                    f"{pod_type}-{index}; replacing onto a non-suspect "
                    "host (suspects sort last in placement)"
                ),
            }
            scheduler.journal.append(
                "health",
                message=action["message"],
                **{k: v for k, v in action.items()
                   if k not in ("kind", "message")},
            )
            scheduler.metrics.incr("health.auto_replace")
            out.append(action)
            break  # at most one automated replace per pass
        return out

    def _pod_on(self, scheduler, host):
        """(pod_type, index) of the remediation target on ``host``:
        a gang member when one runs there (PR 13 semantics, always
        eligible once the seam is enabled), else — only under the
        general ``remediation`` policy gate — any pod instance on
        the host."""
        gang_types = {p.type for p in scheduler.spec.pods if p.gang}
        fallback = None
        for info in scheduler.state_store.fetch_tasks():
            if info.agent_id != host:
                continue
            if info.pod_type in gang_types:
                return (info.pod_type, info.pod_index)
            if fallback is None:
                fallback = (info.pod_type, info.pod_index)
        if self.policy.remediation:
            return fallback
        return None

    # -- the /v1/debug/health block -----------------------------------

    def describe(self) -> dict:
        active = {}
        for pod_type, phase in self.manager._phases.items():
            active[pod_type] = {
                "direction": getattr(phase, "autoscale_direction", "?"),
                "from": getattr(phase, "from_count", None),
                "to": getattr(phase, "to_count", None),
                "phase": phase.name,
                "status": phase.get_status().value,
            }
        return {
            "enabled": self.policy.autoscale,
            "remediation": self.policy.remediation,
            "active": active,
            "cooldowns": {
                f"{pod}:{direction}": round(t, 3)
                for (pod, direction), t in sorted(self._done_t.items())
            },
            "last_replace_t": self._last_replace_t,
            "actions_started": self.actions_started,
            "baselines": dict(self.baselines),
        }
