"""Security plane: secret materialization + TLS certificate issuance.

Reference: the X2 subsystem (dcos/) — SecretsClient.java fetching from
the DC/OS secrets service, CertificateAuthorityClient.java signing
per-task certs consumed by TLSEvaluationStage.java (214 LoC), gated by
the TLSRequiresServiceAccount validator.  TPU-first shape: secrets
resolve through a pluggable provider on the scheduler, certs come from
a CA the scheduler owns, and both land in task sandboxes as 0600 files
shipped over the launch channel (never via env logging or artifacts
URLs).
"""

from dcos_commons_tpu.security.secrets import (
    FileSecretsProvider,
    InMemorySecretsProvider,
    SecretNotFound,
    SecretsProvider,
)
from dcos_commons_tpu.security.tls import CertificateAuthority

__all__ = [
    "CertificateAuthority",
    "FileSecretsProvider",
    "InMemorySecretsProvider",
    "SecretNotFound",
    "SecretsProvider",
]
