"""Element: the common contract of Plan/Phase/Step.

Reference: scheduler/plan/Element.java:18 (name/status/errors),
Interruptible.java (interrupt/proceed), ParentElement.
"""

from __future__ import annotations

import threading
import uuid
from typing import List

from dcos_commons_tpu.plan.status import Status


class Element:
    def __init__(self, name: str):
        self.id = uuid.uuid4().hex
        self.name = name
        self.errors: List[str] = []
        self._lock = threading.RLock()

    # Status ----------------------------------------------------------

    def get_status(self) -> Status:  # pragma: no cover - abstract
        raise NotImplementedError

    @property
    def is_complete(self) -> bool:
        return self.get_status().is_complete

    @property
    def is_pending(self) -> bool:
        return self.get_status() is Status.PENDING

    @property
    def is_running(self) -> bool:
        return self.get_status().is_running

    def has_errors(self) -> bool:
        return bool(self.errors)

    # Interruptible ---------------------------------------------------
    # (reference: Interruptible.java; plans/phases park work via
    #  /v1/plans/<plan>/interrupt, PlansQueries.java:47-231)

    def interrupt(self) -> None:  # pragma: no cover - abstract
        raise NotImplementedError

    def proceed(self) -> None:  # pragma: no cover - abstract
        raise NotImplementedError

    def is_interrupted(self) -> bool:
        return False

    # Restart / force-complete ---------------------------------------

    def restart(self) -> None:  # pragma: no cover - abstract
        raise NotImplementedError

    def force_complete(self) -> None:  # pragma: no cover - abstract
        raise NotImplementedError
