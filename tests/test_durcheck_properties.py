"""Property tests for the durcheck crash-consistency analyzer.

Two properties the analyzer leans on:

* ``DurProgram.propagate()`` is a monotone fixpoint: summaries only
  ever grow, and a second run changes nothing.  Random call graphs
  with random primitive persists/effects pin this down.

* The effect-before-WAL flow is path-join sound: on straight-line
  if/else trees a persist on only one branch never masks an effect
  that reaches the trigger on another path.  We brute-force every
  path through small random statement trees and require the analyzer
  to agree exactly.
"""

import pytest

pytest.importorskip("hypothesis", reason="property tests need the hypothesis package")

from hypothesis import given, settings, strategies as st  # noqa: E402

from dcos_commons_tpu.analysis import durcheck  # noqa: E402
from dcos_commons_tpu.analysis.durcheck import (  # noqa: E402
    DurProgram,
    DurSummary,
    EffectBeforeWalRule,
)

PERSIST_POOL = sorted(durcheck.PERSIST_KINDS)
EFFECT_POOL = sorted(durcheck.EFFECT_KINDS)


# ---------------------------------------------------------------------------
# propagate(): monotone to a fixpoint
# ---------------------------------------------------------------------------

@st.composite
def call_graphs(draw):
    """A random DurProgram: N functions, random direct persists and
    effects, random call edges (cycles allowed — union-by-name in the
    real analyzer makes them common)."""
    n = draw(st.integers(min_value=1, max_value=8))
    names = [f"mod.f{i}" for i in range(n)]
    program = DurProgram()
    for i, name in enumerate(names):
        persists = set(draw(st.lists(st.sampled_from(PERSIST_POOL), max_size=3)))
        effects = set(draw(st.lists(st.sampled_from(EFFECT_POOL), max_size=2)))
        callees = set(
            draw(st.lists(st.sampled_from(names), max_size=4))
        ) - {name}
        simple_callees = {c.rsplit(".", 1)[-1] for c in callees}
        program.add(
            DurSummary(
                qualname=name,
                file="mod.py",
                lineno=i + 1,
                persists=persists,
                effects=effects,
                calls=simple_callees,
                edge_calls=set(simple_callees),
            )
        )
    return program


@settings(max_examples=60, deadline=None)
@given(call_graphs())
def test_propagate_only_grows_summaries(program):
    before = {
        name: (set(s.persists), set(s.effects))
        for name, s in program.functions.items()
    }
    program.propagate()
    for name, summary in program.functions.items():
        pre_persists, pre_effects = before[name]
        assert pre_persists <= summary.persists
        assert pre_effects <= summary.effects
        # transitive closure: every direct callee's post-state is in
        for callee_name in summary.calls:
            for callee in program.resolve(callee_name):
                assert callee.persists <= summary.persists
                assert callee.effects <= summary.effects


@settings(max_examples=60, deadline=None)
@given(call_graphs())
def test_propagate_twice_is_noop(program):
    program.propagate()
    snapshot = {
        name: (set(s.persists), set(s.effects))
        for name, s in program.functions.items()
    }
    rounds = program.propagate()
    assert rounds == 1  # one scan that finds nothing to change
    after = {
        name: (set(s.persists), set(s.effects))
        for name, s in program.functions.items()
    }
    assert after == snapshot


# ---------------------------------------------------------------------------
# dur-effect-before-wal: path-join soundness
# ---------------------------------------------------------------------------
#
# A statement tree is a list of nodes; each node is one of
#   ("effect",)            -> self.task_killer.kill("t")
#   ("trigger",)           -> self.ledger.commit(ops)     (WAL persist)
#   ("noop",)              -> self.log.info("x")
#   ("if", body, orelse)   -> if cond: ... else: ...
#
# Brute force enumerates every path (each If independently takes its
# body or its orelse) and asks: does SOME path run an effect strictly
# before a trigger?  The analyzer must answer exactly the same.

def _leaf():
    return st.sampled_from([("effect",), ("trigger",), ("noop",)])


def _trees(depth):
    if depth == 0:
        return st.lists(_leaf(), min_size=0, max_size=3)
    sub = _trees(depth - 1)
    node = st.one_of(
        _leaf(),
        st.tuples(st.just("if"), sub, sub),
    )
    return st.lists(node, min_size=0, max_size=3)


def _render(stmts, indent):
    pad = " " * indent
    lines = []
    for node in stmts:
        if node[0] == "effect":
            lines.append(pad + 'self.task_killer.kill("t")')
        elif node[0] == "trigger":
            lines.append(pad + "self.ledger.commit(ops)")
        elif node[0] == "noop":
            lines.append(pad + 'self.log.info("x")')
        else:
            _, body, orelse = node
            lines.append(pad + "if self.cond():")
            lines.extend(_render(body, indent + 4) or [pad + "    pass"])
            lines.append(pad + "else:")
            lines.extend(_render(orelse, indent + 4) or [pad + "    pass"])
    return lines


def _paths(stmts):
    """Every linear execution path as a list of 'effect'/'trigger'."""
    acc = [[]]
    for node in stmts:
        if node[0] == "if":
            _, body, orelse = node
            branches = _paths(body) + _paths(orelse)
            acc = [p + b for p in acc for b in branches]
        elif node[0] == "noop":
            continue
        else:
            acc = [p + [node[0]] for p in acc]
    return acc


def _some_path_has_effect_before_trigger(stmts):
    for path in _paths(stmts):
        armed = False
        for step in path:
            if step == "effect":
                armed = True
            elif step == "trigger" and armed:
                return True
    return False


@settings(max_examples=80, deadline=None)
@given(_trees(depth=2))
def test_effect_before_wal_matches_brute_force_paths(stmts):
    body = _render(stmts, indent=8) or ["        pass"]
    src = "class S:\n    def run(self, ops):\n" + "\n".join(body) + "\n"
    result = durcheck.analyze_paths(
        [("/fix/mod.py", "dcos_commons_tpu/scheduler/mod.py", src)],
        rules=[EffectBeforeWalRule()],
    )
    assert result.files_checked == 1  # fixture must parse
    expected = _some_path_has_effect_before_trigger(stmts)
    got = bool(result.findings)
    assert got == expected, (
        f"analyzer={'finding' if got else 'clean'} but brute-force "
        f"paths say {'tainted' if expected else 'clean'}:\n{src}"
    )


def test_persist_on_one_branch_never_masks():
    # The concrete regression the property defends: the branch that
    # persists first must not scrub the effect flowing in from the
    # other branch.
    stmts = [
        ("if", [("trigger",)], [("effect",)]),
        ("trigger",),
    ]
    assert _some_path_has_effect_before_trigger(stmts)
    body = _render(stmts, indent=8)
    src = "class S:\n    def run(self, ops):\n" + "\n".join(body) + "\n"
    result = durcheck.analyze_paths(
        [("/fix/mod.py", "dcos_commons_tpu/scheduler/mod.py", src)],
        rules=[EffectBeforeWalRule()],
    )
    assert len(result.findings) == 1
