"""Property-based tests for the plan status algebra.

plancheck (analysis/plancheck.py) verifies ``aggregate`` on the
multisets the plan state machines actually reach; these properties
pin the algebra down over EVERY multiset up to size 5 — permutation
invariance (a scheduler must report the same plan status regardless
of the order status arrivals interleaved children into the list),
the COMPLETE/ERROR dominance laws, interrupt visibility, and
monotonicity along the working chain.

The old ``aggregate`` failed interrupt visibility — a WAITING child
next to a COMPLETE or DELAYED one was masked behind IN_PROGRESS /
DELAYED — found by plancheck's ``interrupt-visible`` invariant with a
two-event trace (``force_complete(node-0); interrupt(node-1)``) and
fixed in plan/status.py by making WAITING dominate while incomplete.
"""

import itertools

import pytest

pytest.importorskip(
    "hypothesis", reason="property tests need the hypothesis package"
)
from hypothesis import given, settings, strategies as st  # noqa: E402

from dcos_commons_tpu.plan.status import Status, aggregate  # noqa: E402

statuses_up_to_5 = st.lists(
    st.sampled_from(list(Status)), min_size=0, max_size=5
)
interrupted_flag = st.booleans()

# the per-child deployment progression; parents move PENDING ->
# IN_PROGRESS -> COMPLETE as children advance along it
WORKING_CHAIN = [
    Status.PENDING,
    Status.PREPARED,
    Status.STARTING,
    Status.STARTED,
    Status.COMPLETE,
]
_PARENT_RANK = {
    Status.PENDING: 0,
    Status.IN_PROGRESS: 1,
    Status.COMPLETE: 2,
}


@settings(max_examples=400, deadline=None)
@given(statuses_up_to_5, interrupted_flag)
def test_aggregate_is_permutation_invariant(children, interrupted):
    """Order-insensitivity over ALL status multisets up to size 5:
    reordered status arrivals must never change the rollup."""
    base = aggregate(children, interrupted)
    for perm in itertools.permutations(children):
        assert aggregate(perm, interrupted) is base, (
            f"aggregate order-sensitive: {children} -> {base}, "
            f"{list(perm)} -> {aggregate(perm, interrupted)}"
        )


@settings(max_examples=400, deadline=None)
@given(statuses_up_to_5, interrupted_flag)
def test_aggregate_dominance_laws(children, interrupted):
    """ERROR dominates; non-empty all-COMPLETE <=> COMPLETE; an
    incomplete interrupt (parent or child) reads WAITING."""
    got = aggregate(children, interrupted)
    if not children:
        assert got is Status.COMPLETE
        return
    if any(s is Status.ERROR for s in children):
        assert got is Status.ERROR
        return
    if all(s is Status.COMPLETE for s in children):
        assert got is Status.COMPLETE
        return
    assert got is not Status.COMPLETE
    # interrupt visibility: the regression plancheck found — a parked
    # child must surface as WAITING, never hide behind IN_PROGRESS
    if interrupted or any(s is Status.WAITING for s in children):
        assert got is Status.WAITING


@settings(max_examples=400, deadline=None)
@given(
    st.lists(st.sampled_from(WORKING_CHAIN), min_size=1, max_size=5),
    st.integers(min_value=0, max_value=4),
)
def test_aggregate_is_monotone_on_working_chain(children, pick):
    """Advancing one child along PENDING -> ... -> COMPLETE never
    moves the parent BACKWARDS (deploy progress is monotone)."""
    pick %= len(children)
    child = children[pick]
    idx = WORKING_CHAIN.index(child)
    before = aggregate(children)
    for upgrade in WORKING_CHAIN[idx + 1:]:
        advanced = list(children)
        advanced[pick] = upgrade
        after = aggregate(advanced)
        assert _PARENT_RANK[after] >= _PARENT_RANK[before], (
            f"aggregate regressed {before} -> {after} when "
            f"{child} advanced to {upgrade} in {children}"
        )


def test_aggregate_waiting_over_delayed():
    """The specific mix the old code got wrong: an operator interrupt
    next to a crash-loop backoff reads WAITING (the interrupt is the
    operator's own action; the backoff is incidental)."""
    assert aggregate([Status.WAITING, Status.DELAYED]) is Status.WAITING
    assert aggregate([Status.DELAYED, Status.WAITING]) is Status.WAITING
    assert aggregate([Status.WAITING, Status.COMPLETE]) is Status.WAITING
    # no interrupt anywhere: backoff still surfaces when nothing moves
    assert aggregate([Status.DELAYED, Status.COMPLETE]) is Status.DELAYED
