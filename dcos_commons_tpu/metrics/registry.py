"""Counter/gauge registry with Prometheus text exposition.

Reference: metrics/Metrics.java — counters incremented on the hot path
(offers received/processed, revives, declines, suppresses, operation
types, task statuses) and scraped at /v1/metrics/prometheus.  StatsD
push is env-gated as in the reference (STATSD_UDP_HOST/PORT,
Metrics.java:74-79).
"""

from __future__ import annotations

import os
import re
import socket
import threading
import time
from bisect import bisect_left
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple


def percentile(ordered, q: int) -> float:
    """Nearest-rank percentile over an ASCENDING-sorted sequence —
    the one convention shared by the registry's timer aggregates, the
    serve engine's TTFT gauges, and bench percentiles (three copies
    of this formula once disagreed off-by-one at small counts)."""
    n = len(ordered)
    return ordered[min(n - 1, max(0, -(-q * n // 100) - 1))]


# Prometheus metric-name charset: [a-zA-Z_:][a-zA-Z0-9_:]*.  Metric
# names here are dotted and may embed runtime ids with arbitrary
# characters (ha.replication.lag.<puller-id>): everything outside the
# charset becomes "_", and a leading digit gets a "_" prefix — an
# invalid line would make a scraper reject the WHOLE exposition.
_PROM_INVALID = re.compile(r"[^a-zA-Z0-9_:]")


def prometheus_name(name: str) -> str:
    metric = _PROM_INVALID.sub("_", name.lower())
    if metric and metric[0].isdigit():
        metric = "_" + metric
    return metric


# histogram bucket upper bounds (seconds) for timer exposition: the
# offer cycle lives in the 0.1ms..10s band, so a log-ish ladder over
# that range keeps per-record cost to one bisect over 14 floats
TIMER_BUCKETS: Tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


class MetricHistory:
    """Bounded time-series rings over registry snapshots.

    One deque of ``(t, value)`` per metric name, drop-oldest at
    ``capacity`` samples — the "recent past" behind
    ``GET /v1/debug/health``.  Counters additionally support windowed
    rate derivation (``rate()``: delta over the observed window), so a
    monotonic ``offers.evaluated`` reads as evaluations/second without
    a Prometheus server in the loop.  Sampling is driven by the health
    monitor (one ``Metrics.snapshot()`` per sample, time-throttled),
    not per increment: recording N metrics costs N deque appends.
    """

    def __init__(self, capacity: int = 240):
        self.capacity = max(1, int(capacity))
        self._series: Dict[str, deque] = {}
        self._counter_names: set = set()
        self._lock = threading.Lock()

    def record(
        self,
        snapshot: Dict[str, float],
        counter_names=(),
        t: Optional[float] = None,
    ) -> None:
        now = time.time() if t is None else t
        with self._lock:
            self._counter_names.update(counter_names)
            for name, value in snapshot.items():
                series = self._series.get(name)
                if series is None:
                    series = self._series[name] = deque(
                        maxlen=self.capacity
                    )
                series.append((now, float(value)))

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def series(self, name: str, since: float = 0.0) -> List[Tuple[float, float]]:
        """Samples of one metric, oldest first, optionally only those
        at wall time > ``since``."""
        with self._lock:
            samples = list(self._series.get(name, ()))
        if since:
            samples = [s for s in samples if s[0] > since]
        return samples

    def rate(self, name: str, window_s: float = 60.0) -> Optional[float]:
        """Per-second delta of a COUNTER over (up to) the trailing
        window; None for non-counters or <2 samples.  A counter reset
        (registry rebuild) clamps to 0 rather than reporting a huge
        negative rate."""
        with self._lock:
            if name not in self._counter_names:
                return None
            samples = list(self._series.get(name, ()))
        if len(samples) < 2:
            return None
        t1, v1 = samples[-1]
        t0, v0 = samples[0]
        for t, v in reversed(samples):
            if t1 - t > window_s:
                break
            t0, v0 = t, v
        if t1 <= t0:
            return None
        return max(0.0, (v1 - v0) / (t1 - t0))

    def summary(self) -> Dict[str, dict]:
        """One compact row per metric: last value, window min/max,
        sample count, and (counters) the derived rate — the
        ``history`` block of ``/v1/debug/health``."""
        with self._lock:
            names = sorted(self._series)
        out: Dict[str, dict] = {}
        for name in names:
            samples = self.series(name)
            if not samples:
                continue
            values = [v for _, v in samples]
            row = {
                "last": values[-1],
                "min": min(values),
                "max": max(values),
                "n": len(values),
                "span_s": round(samples[-1][0] - samples[0][0], 3),
            }
            rate = self.rate(name)
            if rate is not None:
                row["rate_per_s"] = round(rate, 6)
            out[name] = row
        return out


class Metrics:
    def __init__(self) -> None:
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, Callable[[], float]] = {}
        self._timers: Dict[str, list] = {}
        self._timer_totals: Dict[str, int] = {}
        # monotonic per-timer aggregates for Prometheus: total seconds
        # and per-bucket counts (cumulative at exposition time) — the
        # 256-sample ring re-aggregates and so can only ever be a
        # gauge; rate()/histogram_quantile() need these instead
        self._timer_sums: Dict[str, float] = {}
        self._timer_buckets: Dict[str, List[int]] = {}
        # bounded time-series rings behind /v1/debug/health; sampling
        # is pull-driven (sample_history()), never per-increment
        self.history = MetricHistory()
        self._lock = threading.Lock()
        self._statsd: Optional[socket.socket] = None
        self._statsd_addr = None
        host = os.environ.get("STATSD_UDP_HOST")
        port = os.environ.get("STATSD_UDP_PORT")
        if host and port:
            self._statsd = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            self._statsd_addr = (host, int(port))

    def incr(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0.0) + value
        if self._statsd is not None:
            try:
                self._statsd.sendto(
                    f"{name}:{value}|c".encode(), self._statsd_addr
                )
            except OSError:
                pass

    def gauge(self, name: str, fn: Callable[[], float]) -> None:
        with self._lock:
            self._gauges[name] = fn

    def time(self, name: str):
        """Context manager recording wall seconds (offers.process timer)."""
        registry = self

        class _Timer:
            def __enter__(self):
                self._t0 = time.monotonic()
                return self

            def __exit__(self, *exc):
                elapsed = time.monotonic() - self._t0
                with registry._lock:
                    registry._timers.setdefault(name, []).append(elapsed)
                    del registry._timers[name][:-256]  # ring buffer
                    registry._timer_totals[name] = (
                        registry._timer_totals.get(name, 0) + 1
                    )
                    registry._timer_sums[name] = (
                        registry._timer_sums.get(name, 0.0) + elapsed
                    )
                    buckets = registry._timer_buckets.get(name)
                    if buckets is None:
                        buckets = registry._timer_buckets[name] = (
                            [0] * (len(TIMER_BUCKETS) + 1)
                        )
                    buckets[bisect_left(TIMER_BUCKETS, elapsed)] += 1
                if registry._statsd is not None:
                    # timers push like counters do (reference:
                    # Metrics.getTimer — StatsD timing datagrams in
                    # milliseconds, the `|ms` type)
                    try:
                        registry._statsd.sendto(
                            f"{name}:{elapsed * 1000.0:.3f}|ms".encode(),
                            registry._statsd_addr,
                        )
                    except OSError:
                        pass
                return False

        return _Timer()

    def counters(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._counters)

    def timer_count(self, name: str) -> int:
        """Total recordings of one timer since process start — NOT
        capped by the 256-sample ring, so callers can window samples
        across a phase boundary without index drift."""
        with self._lock:
            return self._timer_totals.get(name, 0)

    def timer_samples(self, name: str, since_count: int = 0) -> list:
        """Copy of the retained samples (newest-last, last 256) for
        one timer, optionally only those recorded after a prior
        ``timer_count()`` reading.  When the ring has trimmed past the
        requested boundary, returns what survives — the newest
        samples, which is what phase-window callers want."""
        with self._lock:
            samples = list(self._timers.get(name, ()))
            fresh = self._timer_totals.get(name, 0) - since_count
        if fresh <= 0:
            return []
        return samples[-fresh:] if fresh < len(samples) else samples

    def snapshot(self) -> Dict[str, float]:
        out = self.counters()
        with self._lock:
            gauges = dict(self._gauges)
            for name, samples in self._timers.items():
                if samples:
                    ordered = sorted(samples)
                    n = len(ordered)
                    mean = sum(ordered) / n
                    out[f"{name}.count"] = float(n)
                    out[f"{name}.min_s"] = ordered[0]
                    out[f"{name}.mean_s"] = mean
                    out[f"{name}.avg_s"] = mean  # legacy alias
                    out[f"{name}.max_s"] = ordered[-1]
                    # nearest-rank p95 over the ring buffer window
                    out[f"{name}.p95_s"] = percentile(ordered, 95)
        for name, fn in gauges.items():
            try:
                out[name] = float(fn())
            except Exception:  # sdklint: disable=swallowed-exception — one broken gauge must not break the whole snapshot/scrape
                pass
        return out

    def sample_history(self, t: Optional[float] = None) -> None:
        """Append one snapshot to the bounded history rings (called by
        the health monitor on its sampling cadence)."""
        with self._lock:
            counter_names = set(self._counters)
        self.history.record(self.snapshot(), counter_names, t=t)

    def prometheus(self) -> str:
        """Prometheus text format (reference: Metrics.java:85-97).

        ``incr()`` entries are monotonic and expose as ``counter`` (so
        ``rate()`` works on them downstream); registered gauges and
        the windowed timer aggregates (min/mean/max/p95 over the
        256-sample ring) expose as ``gauge``.  Each timer additionally
        exposes a full ``histogram`` family — monotonic cumulative
        ``_bucket{le=...}`` counts plus ``_sum``/``_count`` — so
        ``rate()``/``histogram_quantile()`` work downstream (the
        ring's ``.count`` aggregate is superseded by the monotonic
        ``_count`` and skipped here to avoid the name collision).
        Names are sanitized to the Prometheus charset
        (``prometheus_name``): dotted names with embedded runtime ids
        like ``ha.replication.lag.<id>`` must never emit an invalid
        line, and a sanitization collision keeps the first name only
        (duplicate series without labels are invalid too)."""
        with self._lock:
            counter_names = set(self._counters)
            timer_names = set(self._timers)
            timer_totals = dict(self._timer_totals)
            timer_sums = dict(self._timer_sums)
            timer_buckets = {
                name: list(counts)
                for name, counts in self._timer_buckets.items()
            }
        skip = {f"{name}.count" for name in timer_names}
        lines = []
        seen = set()
        for name, value in sorted(self.snapshot().items()):
            if name in skip:
                continue
            metric = prometheus_name(name)
            if metric in seen:
                continue
            seen.add(metric)
            kind = "counter" if name in counter_names else "gauge"
            lines.append(f"# TYPE {metric} {kind}")
            lines.append(f"{metric} {value}")
        for name in sorted(timer_names):
            metric = prometheus_name(name)
            if metric in seen:
                continue
            seen.add(metric)
            lines.append(f"# TYPE {metric} histogram")
            cumulative = 0
            counts = timer_buckets.get(
                name, [0] * (len(TIMER_BUCKETS) + 1)
            )
            for bound, count in zip(TIMER_BUCKETS, counts):
                cumulative += count
                lines.append(
                    f'{metric}_bucket{{le="{bound}"}} {cumulative}'
                )
            lines.append(
                f'{metric}_bucket{{le="+Inf"}} {timer_totals.get(name, 0)}'
            )
            lines.append(f"{metric}_sum {timer_sums.get(name, 0.0)}")
            lines.append(f"{metric}_count {timer_totals.get(name, 0)}")
        return "\n".join(lines) + "\n"
