"""Metrics registry: exposition typing, StatsD push, metric history.

Reference: metrics/Metrics.java — counters AND timers push to StatsD
when STATSD_UDP_HOST/PORT are set (Metrics.java:74-79), and the
Prometheus exposition types monotonic counters as ``counter`` so
downstream ``rate()`` works.  Timers additionally expose a full
histogram family (monotonic ``_bucket``/``_sum``/``_count``), names
are sanitized to the Prometheus charset, and every metric gains a
bounded time-series history ring (the /v1/debug/health substrate).
"""

import re
import socket

from dcos_commons_tpu.metrics.registry import (
    TIMER_BUCKETS,
    MetricHistory,
    Metrics,
    prometheus_name,
)


def test_timer_samples_window_survives_ring_trim():
    """Phase-window callers (bench_fleet_scale) read timer_count()
    before a phase and timer_samples(since_count=...) after; the
    window must stay correct even when the 256-sample ring trims."""
    m = Metrics()
    for _ in range(10):
        with m.time("t"):
            pass
    n0 = m.timer_count("t")
    assert n0 == 10
    assert m.timer_samples("t", since_count=n0) == []
    for _ in range(5):
        with m.time("t"):
            pass
    assert len(m.timer_samples("t", since_count=n0)) == 5
    assert len(m.timer_samples("t")) == 15
    # trim past the boundary: only the retained newest samples return
    for _ in range(300):
        with m.time("t"):
            pass
    windowed = m.timer_samples("t", since_count=n0)
    assert len(windowed) == 256  # ring cap, not 305
    assert m.timer_count("t") == 315


def test_prometheus_types_counters_as_counter():
    m = Metrics()
    m.incr("operations.launch", 3)
    m.incr("task_status.TASK_RUNNING")
    m.gauge("offers.snapshot_cache.hit", lambda: 5.0)
    with m.time("cycle.process"):
        pass
    text = m.prometheus()
    lines = text.splitlines()

    # monotonic incr() entries expose as counter
    assert "# TYPE operations_launch counter" in lines
    assert "operations_launch 3.0" in lines
    assert "# TYPE task_status_task_running counter" in lines
    # registered gauges stay gauges
    assert "# TYPE offers_snapshot_cache_hit gauge" in lines
    # windowed timer aggregates (min/mean/max/p95 over the sample
    # ring) are gauges — the window re-aggregates, so none of them is
    # monotonic; the monotonic side lives in the histogram family
    for suffix in ("min_s", "mean_s", "avg_s", "max_s", "p95_s"):
        assert f"# TYPE cycle_process_{suffix} gauge" in lines
    assert "# TYPE cycle_process histogram" in lines
    # exposition shape: every TYPE line is followed by its first
    # sample (histogram samples carry the _bucket/_sum/_count suffix)
    for i, line in enumerate(lines):
        if line.startswith("# TYPE "):
            metric, kind = line.split()[2], line.split()[3]
            prefix = metric + ("_bucket{" if kind == "histogram"
                               else " ")
            assert lines[i + 1].startswith(prefix), (line, lines[i + 1])


def test_prometheus_timer_histogram_family():
    """Timers expose monotonic ``_bucket{le=...}``/``_sum``/``_count``
    (the satellite fix: nothing monotonic was exported for timers, so
    downstream rate()/histogram_quantile() had nothing to chew on) —
    and the counts survive the 256-sample ring trim."""
    m = Metrics()
    for _ in range(300):
        with m.time("cycle.process"):
            pass
    lines = m.prometheus().splitlines()
    count = [l for l in lines if l.startswith("cycle_process_count ")]
    assert count == ["cycle_process_count 300"]  # NOT the ring's 256
    total = [l for l in lines if l.startswith("cycle_process_sum ")]
    assert total and float(total[0].split()[1]) > 0.0
    # the superseded ring-window .count gauge is skipped (it would
    # collide with the monotonic _count under sanitization)
    assert not any(l.startswith("# TYPE cycle_process_count") for l in lines)
    buckets = [l for l in lines if l.startswith("cycle_process_bucket{")]
    assert len(buckets) == len(TIMER_BUCKETS) + 1
    assert buckets[-1] == 'cycle_process_bucket{le="+Inf"} 300'
    # cumulative monotonicity across the ladder
    counts = [int(l.rsplit(" ", 1)[1]) for l in buckets]
    assert counts == sorted(counts)
    # the snapshot JSON keeps the windowed .count aggregate untouched
    assert m.snapshot()["cycle.process.count"] == 256.0


def test_prometheus_name_sanitization():
    """Names with embedded runtime ids (``ha.replication.lag.<id>``)
    must emit charset-valid lines — one bad line makes a scraper
    reject the whole exposition."""
    assert prometheus_name("ha.replication.lag.standby@2") == \
        "ha_replication_lag_standby_2"
    assert prometheus_name("9lives") == "_9lives"
    m = Metrics()
    m.incr("ha.replication.lag.puller 1/east")
    m.gauge("serving.ttft_p95_s.web:0", lambda: 1.25)
    with m.time("cycle.evaluate"):
        pass
    valid = re.compile(
        r"^(# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* \w+"
        r"|[a-zA-Z_:][a-zA-Z0-9_:]*(\{le=\"[^\"]+\"\})? \S+)$"
    )
    for line in m.prometheus().splitlines():
        assert valid.match(line), line
    # a sanitization collision keeps the first series only (duplicate
    # unlabeled series are invalid too)
    m2 = Metrics()
    m2.incr("offers.a-b")
    m2.incr("offers.a.b")
    lines = m2.prometheus().splitlines()
    assert lines.count("# TYPE offers_a_b counter") == 1


def test_metric_history_rings_and_rates():
    history = MetricHistory(capacity=4)
    for i in range(6):
        history.record(
            {"offers.evaluated": float(10 * i), "cycle.mean_s": 0.5},
            counter_names={"offers.evaluated"},
            t=100.0 + i,
        )
    # bounded drop-oldest ring with timestamps
    series = history.series("offers.evaluated")
    assert len(series) == 4
    assert series[0] == (102.0, 20.0) and series[-1] == (105.0, 50.0)
    assert history.series("offers.evaluated", since=104.0) == \
        [(105.0, 50.0)]
    # counter rate: 10/s over the observed window; non-counters None
    assert abs(history.rate("offers.evaluated") - 10.0) < 1e-9
    assert history.rate("cycle.mean_s") is None
    assert history.rate("never.recorded") is None
    summary = history.summary()
    assert summary["offers.evaluated"]["last"] == 50.0
    assert summary["offers.evaluated"]["rate_per_s"] == 10.0
    assert summary["cycle.mean_s"]["n"] == 4
    assert "rate_per_s" not in summary["cycle.mean_s"]


def test_metric_history_counter_reset_clamps_rate():
    history = MetricHistory()
    history.record({"c": 100.0}, counter_names={"c"}, t=1.0)
    history.record({"c": 5.0}, counter_names={"c"}, t=2.0)  # reset
    assert history.rate("c") == 0.0


def test_registry_sample_history_end_to_end():
    m = Metrics()
    m.incr("offers.evaluated", 5)
    m.gauge("g", lambda: 7.0)
    with m.time("cycle.process"):
        pass
    m.sample_history(t=10.0)
    m.incr("offers.evaluated", 5)
    m.sample_history(t=11.0)
    assert [v for _, v in m.history.series("offers.evaluated")] == \
        [5.0, 10.0]
    assert m.history.rate("offers.evaluated") == 5.0
    assert m.history.series("g")[-1][1] == 7.0
    assert m.history.series("cycle.process.mean_s")


def test_statsd_receives_counter_and_timing_datagrams(monkeypatch):
    sink = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    sink.bind(("127.0.0.1", 0))
    sink.settimeout(5)
    port = sink.getsockname()[1]
    monkeypatch.setenv("STATSD_UDP_HOST", "127.0.0.1")
    monkeypatch.setenv("STATSD_UDP_PORT", str(port))
    try:
        m = Metrics()
        m.incr("offers.evaluated")
        datagram = sink.recv(1024).decode()
        assert datagram == "offers.evaluated:1.0|c"

        # timers push |ms datagrams too (the satellite fix: time()
        # used to record locally and never push)
        with m.time("cycle.evaluate"):
            pass
        datagram = sink.recv(1024).decode()
        name, _, payload = datagram.partition(":")
        assert name == "cycle.evaluate"
        value, _, kind = payload.partition("|")
        assert kind == "ms"
        assert float(value) >= 0.0
    finally:
        sink.close()


def test_no_statsd_configured_is_silent(monkeypatch):
    monkeypatch.delenv("STATSD_UDP_HOST", raising=False)
    monkeypatch.delenv("STATSD_UDP_PORT", raising=False)
    m = Metrics()
    m.incr("x")
    with m.time("y"):
        pass
    assert m.snapshot()["x"] == 1.0
