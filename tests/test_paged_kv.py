"""Paged KV serving (ISSUE 11): allocator/prefix-cache soundness and
the paged engine's greedy equivalence to the slot pool.

Three layers of coverage:

* ALLOCATOR properties (no jax): page conservation, no double-free,
  reservation soundness (``reserved <= available`` so an admitted
  request can never OOM mid-generation), refcounted prefix entries
  freed only at refcount zero, leaf-first LRU eviction — held across
  randomized admit/alloc/register/retire/abandon sequences by a
  hypothesis sweep calling ``check_invariants`` after every op.

* ENGINE properties against a deterministic fake model: chunked
  prefill reproduces the oracle chain for any prompt length / chunk
  width, page-budget exhaustion queues (FIFO) and completes, the
  budget-starved 503 carries the distinct kv-page-budget reason and
  lands in the requests_timed_out_memory split, and — the
  copy-on-write contract — no physical page is ever written after it
  was published into the prefix cache.

* REAL-MODEL equivalence (tiny flagship on CPU): tokens produced by
  the paged engine — chunked prefill, page-table attention, prefix-
  cache hits, mixed chunked/unchunked admission — are IDENTICAL to
  whole-batch ``generate`` / the slot-pool path on the same prompts,
  including through the gang driver's paged broadcast protocol
  executed for real in a single-process gang sim.
"""

import importlib.util
import os
import threading
import time

import numpy as np
import pytest

from dcos_commons_tpu.serve.engine import PagedEngine
from dcos_commons_tpu.serve.paging import (
    PageAllocator,
    paged_config_from_env,
    worst_case_pages,
)
from dcos_commons_tpu.utils.microbatch import QueueTimeoutError

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- allocator unit + property coverage --------------------------------


def test_allocator_admit_reserve_alloc_retire_roundtrip():
    a = PageAllocator(pages=8, page_tokens=4)
    adm = a.admit([1, 2, 3, 4, 5], max_new=4)  # worst: ceil(8/4) = 2
    assert adm is not None and adm.reserve_left == 2
    assert a.reserved_pages == 2
    pages = [a.alloc(adm), a.alloc(adm)]
    assert a.reserved_pages == 0
    with pytest.raises(RuntimeError):
        a.alloc(adm)  # past the worst case: engine bug, loud
    a.retire(adm, pages)
    assert a.free_pages == 8 and a.reserved_pages == 0
    a.check_invariants()


def test_allocator_admission_denied_when_budget_reserved():
    a = PageAllocator(pages=4, page_tokens=4)
    adm = a.admit([1] * 4, max_new=13)  # worst: ceil(16/4) = 4 pages
    assert adm is not None
    assert a.admit([2], max_new=1) is None  # 1 page needed, 0 left
    assert not a.would_admit([2], max_new=1)
    a.retire(adm, [])
    assert a.would_admit([2], max_new=1)


def test_allocator_double_free_and_foreign_free_raise():
    a = PageAllocator(pages=4, page_tokens=2)
    adm = a.admit([1, 2], max_new=2)
    page = a.alloc(adm)
    a.free_page(page)
    with pytest.raises(RuntimeError, match="double free"):
        a.free_page(page)
    with pytest.raises(RuntimeError):
        a.free_page(0)  # the trash page is never owned


def test_prefix_chain_register_match_refcount_and_leaf_eviction():
    a = PageAllocator(pages=6, page_tokens=2)
    adm = a.admit([1, 2, 3, 4, 9], max_new=2)  # matches nothing yet
    p1, p2 = a.alloc(adm), a.alloc(adm)
    assert a.register(adm, (1, 2), p1)
    assert a.register(adm, (3, 4), p2)
    # registered pages are cache-owned: a private free must refuse
    with pytest.raises(RuntimeError, match="prefix cache"):
        a.free_page(p1)
    a.retire(adm, [])
    # zero refs: the WHOLE chain is reclaimable (refcounts are
    # monotone down a chain, so leaf-first eviction reaches it all)
    assert a.cached_pages == 2 and a.reclaimable_pages == 2
    # a second identical prefix pins the chain (refs > 0 again)
    adm2 = a.admit([1, 2, 3, 4, 9], max_new=2)
    assert adm2.cached_pages == 2
    assert a.reclaimable_pages == 0
    a.retire(adm2, [])
    # more than free + reclaimable can ever supply: denied outright
    assert a.admit([5, 5], max_new=13) is None  # worst: 7 > 6
    # eviction under pressure: the LEAF (3,4) goes first, then (1,2)
    adm3 = a.admit([7, 8], max_new=11)  # worst: ceil(12/2) = 6 pages
    assert adm3 is not None  # 4 free + 2 reclaimable = 6
    held = [a.alloc(adm3) for _ in range(6)]
    assert a.cached_pages == 0  # both entries evicted, leaf first
    assert a.evictions == 2
    a.retire(adm3, held)
    a.check_invariants()


def test_register_duplicate_key_keeps_page_private_and_closes_chain():
    a = PageAllocator(pages=8, page_tokens=2)
    adm1 = a.admit([1, 2, 3, 4, 5], max_new=2)
    q1, q2 = a.alloc(adm1), a.alloc(adm1)
    assert a.register(adm1, (1, 2), q1)
    assert a.register(adm1, (3, 4), q2)
    # a concurrent identical prompt that matched NOTHING (admitted
    # before registration) tries to publish the same keys
    a2 = PageAllocator(pages=8, page_tokens=2)  # fresh: simulate race
    adm_a = a2.admit([1, 2, 3, 4, 5], max_new=2)
    adm_b = a2.admit([1, 2, 3, 4, 5], max_new=2)
    pa1, pb1 = a2.alloc(adm_a), a2.alloc(adm_b)
    assert a2.register(adm_a, (1, 2), pa1)
    assert not a2.register(adm_b, (1, 2), pb1)  # duplicate: private
    assert not adm_b.chain_open
    pb2 = a2.alloc(adm_b)
    # chain closed: deeper pages stay private too
    assert not a2.register(adm_b, (3, 4), pb2)
    a2.retire(adm_b, [pb1, pb2])
    a2.retire(adm_a, [])
    a2.check_invariants()


def test_allocator_property_random_lifecycles_conserve_pages():
    hypothesis = pytest.importorskip("hypothesis")
    st = hypothesis.strategies

    @hypothesis.given(
        st.lists(
            st.tuples(
                st.lists(st.integers(0, 3), min_size=1, max_size=12),
                st.integers(1, 6),    # max_new
                st.integers(0, 100),  # progress % before retire
                st.booleans(),        # abandon (retire with no allocs)
            ),
            min_size=1, max_size=24,
        ),
        st.integers(2, 12),  # pages
        st.integers(1, 4),   # page_tokens
    )
    @hypothesis.settings(max_examples=120, deadline=None)
    def run(jobs, pages, page_tokens):
        a = PageAllocator(pages, page_tokens)
        live = []  # (admission, private_pages, prompt, progress plan)

        def all_private():
            return [p for _, pp, _ in live for p in pp]

        for prompt, max_new, pct, abandon in jobs:
            worst = worst_case_pages(len(prompt), max_new, page_tokens)
            if worst > pages:
                continue  # submit-time 400, never reaches admission
            adm = a.admit(prompt, max_new)
            if adm is None:
                # budget-blocked: retire the oldest live request and
                # retry once (the engine's FIFO drain, compressed)
                if live:
                    old_adm, old_pages, _ = live.pop(0)
                    a.retire(old_adm, old_pages)
                    a.check_invariants(all_private())
                    adm = a.admit(prompt, max_new)
                if adm is None:
                    continue
            private = []
            live.append((adm, private, prompt))
            a.check_invariants(all_private())
            if abandon:
                live.pop()
                a.retire(adm, private)
                a.check_invariants(all_private())
                continue
            # consume part of the reservation, registering full
            # prompt pages as they complete (the engine's chunk walk)
            to_alloc = (adm.reserve_left * pct) // 100
            v = adm.cached_pages
            for _ in range(to_alloc):
                page = a.alloc(adm)
                private.append(page)
                a.check_invariants(all_private())
                covered = (v + 1) * page_tokens
                if covered <= len(prompt):
                    toks = tuple(
                        prompt[v * page_tokens:covered]
                    )
                    if a.register(adm, toks, page):
                        private.remove(page)
                    a.check_invariants(all_private())
                v += 1
        for adm, private, _ in live:
            a.retire(adm, private)
        a.check_invariants([])
        # everything returned: free + resident cache == total
        assert a.free_pages + a.cached_pages == pages
        assert a.reserved_pages == 0
        assert a.cached_pages == a.reclaimable_pages + sum(
            1 for e in a._by_id.values() if e.children
        )

    run()


def test_paged_config_from_env_contract():
    from dcos_commons_tpu.specification.specs import SpecError

    cfg = paged_config_from_env({"MAX_LEN": "64", "SERVE_BATCH": "4"})
    assert cfg.page_tokens == 16 and cfg.pages == 16  # 4 * ceil(64/16)
    assert cfg.pages_per_row == 4 and cfg.arena_pages == 17
    assert paged_config_from_env({"KV_PAGE_TOKENS": "0"}) is None
    with pytest.raises(SpecError, match="overcommitted"):
        paged_config_from_env({
            "MAX_LEN": "64", "KV_PAGES": "2", "KV_PAGE_TOKENS": "16",
        })
    with pytest.raises(SpecError):
        paged_config_from_env({"PREFILL_CHUNK_TOKENS": "-1"})
    off = paged_config_from_env({"PREFIX_CACHE": "0"})
    assert off.prefix_cache is False


# -- engine vs a deterministic fake model ------------------------------


_V = 97


def _chain_first(prompt):
    return (sum(prompt) * 31 + len(prompt)) % _V


def _chain_next(tok, pos):
    return (tok * 7 + pos * 3 + 1) % _V


def _chain_oracle(prompt, n, eos=None):
    out = [_chain_first(prompt)]
    pos = len(prompt)
    while len(out) < n and (eos is None or out[-1] != eos):
        out.append(_chain_next(out[-1], pos))
        pos += 1
    if eos is not None and eos in out:
        out = out[: out.index(eos) + 1]
    return out


class FakePagedModel:
    """Chunk-accumulating fake: chunks of one slot's prompt arrive in
    order (prefix cache OFF keeps start=0 on the first chunk), the
    final chunk's return is the chain's first token.  Decode asserts
    every live row's write page is allocated (nonzero)."""

    def __init__(self, step_gate=None):
        self.partial = {}
        self.step_gate = step_gate
        self.decode_calls = 0
        self.max_active = 0

    def prefill_chunk(self, padded, slot, table, start, true_len,
                      temp, seed):
        if start == 0:
            self.partial[slot] = []
        buf = self.partial[slot]
        assert len(buf) == start, "chunks arrived out of order"
        buf.extend(int(t) for t in padded[0, :true_len])
        # the chunk's pages must be allocated before the model runs
        p = 4  # matches the engines below
        for pos in range(start, start + true_len):
            assert table[pos // p] != 0, "write into unallocated page"
        return _chain_first(buf)

    def decode(self, tok, pos, temps, seeds, tables, n_active):
        if self.step_gate is not None:
            assert self.step_gate.wait(10), "tick never released"
            self.step_gate.clear()
        self.decode_calls += 1
        self.max_active = max(self.max_active, n_active)
        p = 4
        for s in range(len(tok)):
            if pos[s] > 0:  # live row: write page must exist
                assert tables[s][int(pos[s]) // p] != 0
        return np.asarray(
            [_chain_next(int(t), int(q)) for t, q in zip(tok, pos)],
            np.int32,
        )


def _paged_engine(model, slots, pages, max_len=32, prompt_len=24,
                  chunk=5, prefix=False, **kw):
    return PagedEngine(
        model.prefill_chunk, model.decode, slots, max_len, prompt_len,
        page_tokens=4, pages=pages, chunk_tokens=chunk,
        prefix_cache=prefix, **kw,
    )


def _swarm(engine, jobs):
    results = [None] * len(jobs)
    errors = []

    def client(i):
        rows, n, eos = jobs[i]
        try:
            results[i] = engine.submit(rows, n, eos_id=eos)
        except Exception as e:  # noqa: BLE001 — surfaced via assert
            errors.append(e)

    threads = [
        threading.Thread(target=client, args=(i,))
        for i in range(len(jobs))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not errors, errors
    return results


def test_paged_engine_chunked_prefill_matches_oracle():
    model = FakePagedModel()
    engine = _paged_engine(model, slots=3, pages=24)
    try:
        jobs = [
            ([[1, 2, 3]], 8, None),               # single chunk
            ([list(range(1, 14))], 5, None),      # 13 tokens: 3 chunks
            ([[4], [5, 6]], 5, None),
            ([list(range(2, 20))], 6, None),      # 18 tokens: 4 chunks
        ]
        results = _swarm(engine, jobs)
        for (rows, n, eos), result in zip(jobs, results):
            assert result == [_chain_oracle(r, n, eos) for r in rows]
        stats = engine.stats()
        assert stats["active_slots"] == 0
        assert stats["kv_pages_free"] == 24  # prefix off: all freed
        assert stats["prefill_chunk_backlog"] == 0
        engine._allocator.check_invariants()
    finally:
        engine.stop()


def test_paged_engine_budget_exhaustion_queues_fifo_and_completes():
    """More worst-case page demand than the arena: the overflow WAITS
    for retirements (strict FIFO, no starvation, no mid-flight OOM)
    and every chain still matches the oracle."""
    model = FakePagedModel()
    # 8 pages of 4: each job below worst-cases 3 pages, so at most 2
    # run concurrently even though 4 decode rows exist
    engine = _paged_engine(model, slots=4, pages=8, max_len=12,
                           prompt_len=8)
    try:
        jobs = [([[i + 1, i + 2]], 8, None) for i in range(7)]
        results = _swarm(engine, jobs)
        for (rows, n, eos), result in zip(jobs, results):
            assert result == [_chain_oracle(rows[0], n, eos)]
        assert model.max_active <= 2
        stats = engine.stats()
        assert stats["kv_pages_free"] == 8
        assert stats["kv_pages_reserved"] == 0
    finally:
        engine.stop()


def test_paged_timeout_names_the_starved_resource():
    """A budget-starved request 503s with the kv-page-budget reason
    (the requests_timed_out_memory split); a slot-starved one keeps
    the kv-slot reason (compute split)."""
    gate = threading.Event()  # never set: decode wedges
    model = FakePagedModel(step_gate=gate)
    # 4 pages: the occupant's worst case takes them all; slots ample
    engine = _paged_engine(model, slots=3, pages=4, max_len=16,
                           prompt_len=8, queue_timeout_s=0.3)
    try:
        occupant = threading.Thread(
            target=lambda: pytest.raises(
                Exception, engine.submit, [[9, 9]], 14
            ),
            daemon=True,
        )
        occupant.start()
        time.sleep(0.1)
        with pytest.raises(QueueTimeoutError) as exc:
            engine.submit([[5]], 4)
        assert exc.value.kind == "kv-page-budget"
        assert "page budget" in str(exc.value)
        deadline = time.monotonic() + 5
        while (engine.stats()["requests_timed_out"] < 2
               and time.monotonic() < deadline):
            time.sleep(0.01)
        stats = engine.stats()
        assert stats["requests_timed_out_memory"] == 1
        assert stats["requests_timed_out_compute"] == 1  # the stalled
    finally:
        gate.set()
        engine.stop()
    # slot starvation: pages ample, one decode row, wedged occupant
    gate2 = threading.Event()
    model2 = FakePagedModel(step_gate=gate2)
    engine2 = _paged_engine(model2, slots=1, pages=24,
                            queue_timeout_s=0.3)
    try:
        occupant = threading.Thread(
            target=lambda: pytest.raises(
                Exception, engine2.submit, [[9]], 8
            ),
            daemon=True,
        )
        occupant.start()
        time.sleep(0.1)
        with pytest.raises(QueueTimeoutError) as exc:
            engine2.submit([[5]], 4)
        assert exc.value.kind == "kv-slot"
        assert engine2.stats()["requests_timed_out_memory"] == 0
    finally:
        gate2.set()
        engine2.stop()


def test_paged_long_prefill_is_progress_not_a_stall():
    """A prompt whose CHUNKED prefill spans several timeout windows
    must not be cut off as 'stalled': chunk progress is progress."""
    model = FakePagedModel()
    orig = model.prefill_chunk

    def slow_chunk(*a, **kw):
        time.sleep(0.15)  # half a window per chunk
        return orig(*a, **kw)

    model.prefill_chunk = slow_chunk
    engine = _paged_engine(model, slots=1, pages=24, chunk=3,
                           queue_timeout_s=0.3)
    try:
        # 15 tokens / 3-token chunks = 5 chunks ~= 0.75s > 2 windows
        prompt = list(range(1, 16))
        got = engine.submit([prompt], 4)[0]
        assert got == _chain_oracle(prompt, 4)
        assert engine.stats()["requests_timed_out"] == 0
    finally:
        engine.stop()


def test_paged_cow_no_write_after_page_published():
    """The copy-on-write contract, audited on the engine's own
    thread: once a page is registered into the prefix cache, no model
    call may ever write to it again.  Identical prompts hammer the
    cache while the audit records every write and every
    registration."""
    events = []  # ("write", page) / ("reg", page), loop-thread order

    class AuditModel(FakePagedModel):
        def prefill_chunk(self, padded, slot, table, start, true_len,
                          temp, seed):
            p = 4
            for pos in range(start, start + true_len):
                events.append(("write", int(table[pos // p])))
            if start == 0:
                self.partial[slot] = []
            buf = self.partial.setdefault(slot, [])
            # cache hits skip earlier chunks: pad the buffer (token
            # values untracked — this test audits pages, not tokens)
            buf.extend([0] * (start - len(buf)))
            buf.extend(int(t) for t in padded[0, :true_len])
            return _chain_first(buf)

        def decode(self, tok, pos, temps, seeds, tables, n_active):
            p = 4
            self.decode_calls += 1
            for s in range(len(tok)):
                if pos[s] > 0:
                    events.append(
                        ("write", int(tables[s][int(pos[s]) // p]))
                    )
            return np.asarray(
                [_chain_next(int(t), int(q))
                 for t, q in zip(tok, pos)],
                np.int32,
            )

    model = AuditModel()
    engine = _paged_engine(model, slots=3, pages=24, prefix=True)
    reg_orig = engine._allocator.register

    def audited_register(adm, toks, page):
        ok = reg_orig(adm, toks, page)
        if ok:
            events.append(("reg", int(page)))
        return ok

    engine._allocator.register = audited_register
    try:
        prompt = list(range(1, 12))  # 2 full pages + a partial
        jobs = [([prompt], 6, None) for _ in range(5)]
        jobs += [([prompt + [77]], 6, None)]  # diverges mid-page 3
        _swarm(engine, jobs)
        assert engine.stats()["prefix_cache_hits"] > 0
        published_at = {}
        for i, (kind, page) in enumerate(events):
            if kind == "reg":
                published_at.setdefault(page, i)
        for i, (kind, page) in enumerate(events):
            if kind == "write" and page in published_at:
                assert i < published_at[page], (
                    f"page {page} written at event {i} after being "
                    f"published at {published_at[page]}"
                )
        engine._allocator.check_invariants()
    finally:
        engine.stop()


def test_paged_engine_property_any_request_mix_matches_oracle():
    hypothesis = pytest.importorskip("hypothesis")
    st = hypothesis.strategies

    @hypothesis.given(
        st.lists(
            st.tuples(
                st.lists(
                    st.lists(st.integers(0, _V - 1), min_size=1,
                             max_size=9),
                    min_size=1, max_size=3,
                ),
                st.integers(1, 8),
                st.one_of(st.none(), st.integers(0, _V - 1)),
            ),
            min_size=1, max_size=6,
        ),
        st.integers(1, 4),   # slots
        st.integers(3, 10),  # pages (>= one worst-case request: 3)
        st.integers(1, 6),   # chunk width
    )
    @hypothesis.settings(
        max_examples=40, deadline=None,
        suppress_health_check=[hypothesis.HealthCheck.too_slow],
    )
    def run(jobs, slots, pages, chunk):
        max_len = 12
        # clamp the requested length to what the 12-position virtual
        # row can hold (over-length asks are a submit-time 400, not
        # this test's subject)
        jobs = [
            (rows, min(n, max_len - max(len(r) for r in rows)), eos)
            for rows, n, eos in jobs
        ]
        jobs = [j for j in jobs if j[1] >= 1]
        if not jobs:
            return
        model = FakePagedModel()
        engine = _paged_engine(
            model, slots=slots, pages=pages, max_len=max_len,
            prompt_len=9, chunk=chunk,
        )
        try:
            results = _swarm(engine, jobs)
            for (rows, n, eos), result in zip(jobs, results):
                assert result == [
                    _chain_oracle(r, n, eos) for r in rows
                ]
            stats = engine.stats()
            assert stats["active_slots"] == 0
            assert stats["queue_depth"] == 0
            assert stats["kv_pages_free"] == pages
            assert stats["kv_pages_reserved"] == 0
            engine._allocator.check_invariants()
        finally:
            engine.stop()

    run()


# -- admission gate: page-budget overcommit is a 422, not a 503 --------


def test_admission_gate_rejects_page_budget_overcommit():
    """The PR 9 admission gate runs the serve workload builders, so
    an arena that cannot hold one MAX_LEN request (a permanent-503
    misconfiguration) is a line-anchored 422 finding at PUT time."""
    jax = pytest.importorskip("jax")  # noqa: F841 — builder needs it

    from dcos_commons_tpu.multi.admission import validate_service_yaml

    yaml_text = """
name: badserve
pods:
  server:
    count: 1
    tpu:
      generation: v5e
      chips-per-host: 1
    tasks:
      api:
        goal: RUNNING
        cmd: "python serve_worker.py"
        cpus: 1
        memory: 1024
        env:
          VOCAB: "512"
          D_MODEL: "64"
          N_LAYERS: "2"
          MAX_LEN: "256"
          SERVE_BATCH: "4"
          KV_PAGE_TOKENS: "16"
          KV_PAGES: "3"
"""
    _spec, findings = validate_service_yaml(yaml_text, "badserve")
    assert any(
        "overcommitted" in f.render() for f in findings
    ), [f.render() for f in findings]
    good = yaml_text.replace('KV_PAGES: "3"', 'KV_PAGES: "64"')
    _spec, findings = validate_service_yaml(good, "badserve")
    assert not [
        f for f in findings if "overcommit" in f.render()
    ], [f.render() for f in findings]


# -- SLO watcher: the min-direction kv_pages_free signal ---------------


def test_slo_watcher_kv_pages_free_breaches_below_minimum():
    from dcos_commons_tpu.health.detectors import ServingSloWatcher

    w = ServingSloWatcher(kv_pages_free_slo=5)
    events = w.observe({"serve-0-task": {"kv_pages_free": 2}})
    assert len(events) == 1 and not events[0].get("cleared")
    assert events[0]["signal"] == "kv_pages_free"
    assert "below minimum" in events[0]["message"]
    # still breaching: no repeat, magnitude tracked
    assert w.observe({"serve-0-task": {"kv_pages_free": 1}}) == []
    assert w.breaches[("serve-0-task", "kv_pages_free")] == 1
    # recovery clears
    events = w.observe({"serve-0-task": {"kv_pages_free": 9}})
    assert len(events) == 1 and events[0]["cleared"]
    # per-task env override beats the scheduler default
    w2 = ServingSloWatcher(kv_pages_free_slo=0)  # disabled by default
    assert w2.observe({"t": {"kv_pages_free": 1}}) == []
    events = w2.observe(
        {"t": {"kv_pages_free": 1}},
        env_by_task={"t": {"SERVE_KV_PAGES_FREE_SLO": "4"}},
    )
    assert len(events) == 1


# -- real model: token-identical to the slot pool ----------------------


@pytest.fixture(scope="module")
def tiny():
    import jax
    import jax.numpy as jnp

    from dcos_commons_tpu.models import TransformerConfig, init_params

    config = TransformerConfig(
        vocab=64, d_model=32, n_layers=2, n_heads=8, n_kv_heads=4,
        d_ff=96, max_seq=64, dtype=jnp.float32, remat=False,
    )
    return config, init_params(config, jax.random.key(0))


MAX_LEN, NEW = 48, 8
PROMPT_LEN = MAX_LEN - NEW
PROMPTS = [
    [1, 2, 3, 4],                             # shorter than a chunk
    [9, 8],
    [5, 6, 7, 2, 1],
    [3],
    [11, 12, 13, 14, 15, 16, 17, 2, 9],       # 9 tokens: 2 chunks
]


def _oracle(config, params, prompt, n):
    import jax.numpy as jnp

    from dcos_commons_tpu.models import generate

    out = generate(
        config, params, jnp.asarray([prompt], jnp.int32),
        max_new_tokens=n,
    )
    return [int(t) for t in out[0]]


def _real_paged(config, params, kv_dtype="native", slots=3, pages=30,
                page_tokens=4, chunk=6, prefix=True, **kw):
    from dcos_commons_tpu.serve.pool import PagedPoolModel

    pool = PagedPoolModel(
        config, params, slots, MAX_LEN, page_tokens, pages, chunk,
        kv_dtype=kv_dtype,
    )
    pool.warm()
    engine = PagedEngine(
        pool.prefill_chunk, pool.decode, slots, MAX_LEN, PROMPT_LEN,
        page_tokens=page_tokens, pages=pages, chunk_tokens=chunk,
        prefix_cache=prefix, queue_timeout_s=120, **kw,
    )
    return pool, engine


@pytest.mark.parametrize("kv_dtype", ["native", "int8"])
def test_paged_engine_greedy_equals_whole_batch_generate(tiny, kv_dtype):
    """Staggered concurrent admission over the paged arena — mixed
    chunked/unchunked prompts, page tables, early retirement —
    reproduces whole-batch generate token for token (the slot pool's
    own equivalence bar, held by the paged path)."""
    config, params = tiny
    _pool, engine = _real_paged(config, params, kv_dtype=kv_dtype)
    try:
        results = [None] * len(PROMPTS)
        errors = []

        def client(i):
            try:
                results[i] = engine.submit([PROMPTS[i]], NEW)[0]
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [
            threading.Thread(target=client, args=(i,))
            for i in range(len(PROMPTS))
        ]
        for t in threads:
            t.start()
            time.sleep(0.01)  # staggered arrivals: mid-flight admission
        for t in threads:
            t.join(timeout=120)
        assert not errors, errors
        if kv_dtype == "native":
            oracles = [
                _oracle(config, params, p, NEW) for p in PROMPTS
            ]
            assert results == oracles
        else:
            # int8 equivalence is engine-vs-engine determinism, as in
            # the slot-pool tests
            again = [engine.submit([p], NEW)[0] for p in PROMPTS]
            assert results == again
        engine._allocator.check_invariants()
    finally:
        engine.stop()


def test_paged_prefix_cache_hit_is_token_identical(tiny):
    """A request served partly from CACHED prompt pages produces the
    same tokens as the cold path — shared pages carry bit-identical
    K/V, and divergence past the shared prefix recomputes privately."""
    config, params = tiny
    _pool, engine = _real_paged(config, params)
    shared = [7, 3, 9, 1, 4, 4, 2, 8]  # exactly 2 full pages (P=4)
    variants = [
        shared + [5],
        shared + [6, 1, 2],
        shared + [5],          # full repeat: max cache reuse
        shared[:6] + [9, 9],   # diverges MID page 2: partial miss
    ]
    try:
        cold = engine.submit([variants[0]], NEW)[0]
        base = engine.stats()["prefix_cache_hits"]
        for v in variants:
            got = engine.submit([v], NEW)[0]
            assert got == _oracle(config, params, v, NEW)
        assert cold == _oracle(config, params, variants[0], NEW)
        stats = engine.stats()
        assert stats["prefix_cache_hits"] > base
        assert 0.0 < stats["prefix_cache_hit_rate"] <= 1.0
        assert stats["kv_pages_cached"] > 0
        engine._allocator.check_invariants()
    finally:
        engine.stop()


def test_paged_vs_slot_pool_same_tokens_same_load(tiny):
    """The two engines, same prompts, same greedy request mix: token
    outputs must be IDENTICAL (the bench's equality fence, held as a
    unit test)."""
    from dcos_commons_tpu.serve.engine import SlotEngine
    from dcos_commons_tpu.serve.pool import PoolModel

    config, params = tiny
    slot_pool = PoolModel(config, params, 3, MAX_LEN)
    slot_engine = SlotEngine(
        slot_pool.prefill, slot_pool.decode, 3, MAX_LEN, PROMPT_LEN,
        queue_timeout_s=120,
    )
    _pool, paged_engine = _real_paged(config, params)
    try:
        slot_out = [slot_engine.submit([p], NEW)[0] for p in PROMPTS]
        paged_out = [paged_engine.submit([p], NEW)[0] for p in PROMPTS]
        assert slot_out == paged_out
    finally:
        slot_engine.stop()
        paged_engine.stop()


def test_paged_gang_sim_broadcast_protocol_equivalence(tiny):
    """The gang driver's PAGED broadcast protocol (chunk/page fields)
    executed for real in a single-process gang sim: rank 0's engine
    callbacks broadcast each tick and _execute_paged_tick runs the
    identical payload — greedy replies stay token-identical."""
    from jax.experimental import multihost_utils

    from dcos_commons_tpu.serve.pool import PagedPoolModel

    path = os.path.join(REPO, "frameworks", "jax",
                        "serve_gang_worker.py")
    spec = importlib.util.spec_from_file_location(
        "gang_worker_paged_ut", path
    )
    gw = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(gw)

    config, params = tiny
    slots, p_tok, pages, chunk = 3, 4, 30, 6
    m = -(-MAX_LEN // p_tok)
    pool = PagedPoolModel(
        config, params, slots, MAX_LEN, p_tok, pages, chunk
    )
    pool.warm()
    ticks = {"admit": 0, "decode": 0, "noop": 0}

    def prefill_fn(padded, slot, table, start, true_len, temp, seed):
        head = np.asarray(
            [gw.OP_ADMIT, slot, start, true_len, seed,
             round(temp * 1e6)],
            np.int64,
        )
        _, zero_rows, zero_tables, _ = gw._zero_paged_payload(
            slots, m, chunk
        )
        zero_tables[slot] = table
        out = gw._broadcast_paged_tick(
            multihost_utils,
            (head, zero_rows, zero_tables, padded.astype(np.int32)),
            slots, m, chunk,
        )
        ticks["admit"] += 1
        return gw._execute_paged_tick(pool, *out)

    def decode_fn(tok, pos, temps, seeds, tables, n_active):
        head = np.asarray(
            [gw.OP_DECODE, n_active, 0, 0, 0, 0], np.int64
        )
        rows = np.stack([
            tok.astype(np.int64), pos.astype(np.int64),
            np.round(temps.astype(np.float64) * 1e6).astype(np.int64),
            seeds.astype(np.int64),
        ], axis=1)
        out = gw._broadcast_paged_tick(
            multihost_utils,
            (head, rows, tables.astype(np.int64),
             np.zeros((1, chunk), np.int32)),
            slots, m, chunk,
        )
        ticks["decode"] += 1
        return gw._execute_paged_tick(pool, *out)

    def idle():
        out = gw._broadcast_paged_tick(
            multihost_utils, None, slots, m, chunk
        )
        assert gw._execute_paged_tick(pool, *out) is None
        ticks["noop"] += 1

    engine = PagedEngine(
        prefill_fn, decode_fn, slots, MAX_LEN, PROMPT_LEN,
        page_tokens=p_tok, pages=pages, chunk_tokens=chunk,
        queue_timeout_s=120, on_idle=idle, idle_every_s=0.01,
    )
    try:
        results = engine.submit(PROMPTS, NEW)
        oracles = [_oracle(config, params, p, NEW) for p in PROMPTS]
        assert results == oracles
        assert ticks["admit"] >= len(PROMPTS)  # >= 1 chunk each
        assert ticks["decode"] >= NEW - 1
        deadline = time.monotonic() + 5
        while not ticks["noop"] and time.monotonic() < deadline:
            time.sleep(0.01)
        assert ticks["noop"] >= 1
    finally:
        engine.stop()
