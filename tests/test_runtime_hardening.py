"""Runtime hardening: revive throttling, crash-to-restart on wedged
loops, fail-loud gang relaunch without a rendezvous point.

Reference: framework/ReviveManager.java + TokenBucket.java (revive
rate limit); SchedulerConfig.java deadlock-exit semantics (a wedged
scheduler exits for supervised restart rather than looping silently).
"""

from dcos_commons_tpu.runtime.token_bucket import TokenBucket
from dcos_commons_tpu.testing import (
    AdvanceCycles,
    ExpectDeploymentComplete,
    SendTaskFailed,
    SendTaskRunning,
    ServiceTestRunner,
)

ONE_POD_YAML = """
name: throttle-svc
pods:
  app:
    count: 1
    tasks:
      main:
        goal: RUNNING
        cmd: "serve"
        cpus: 0.1
        memory: 32
"""


def test_revive_throttled_by_token_bucket():
    """A crash-looping task may not force a revive every cycle: the
    second revive inside the refill window is throttled, then proceeds
    once the bucket refills."""
    runner = ServiceTestRunner(ONE_POD_YAML)
    runner.run([
        AdvanceCycles(1),
        SendTaskRunning("app-0-main"),
        ExpectDeploymentComplete(),
    ])
    scheduler = runner.world.scheduler
    clock = [0.0]
    scheduler.revive_bucket = TokenBucket(
        capacity=1, refill_interval_s=100.0, clock=lambda: clock[0]
    )
    scheduler.run_cycle()  # no candidates -> suppressed
    assert scheduler._suppressed

    runner.run([SendTaskFailed("app-0-main"), AdvanceCycles(2)])
    # first revive consumed the only token; relaunch happened
    assert scheduler.metrics.counters()["revives"] == 1
    assert len(runner.agent.launches_of("app-0-main")) == 2
    runner.run([SendTaskRunning("app-0-main"), AdvanceCycles(1)])
    assert scheduler._suppressed

    runner.run([SendTaskFailed("app-0-main"), AdvanceCycles(3)])
    # bucket empty: revive throttled, no relaunch
    assert scheduler.metrics.counters()["revives.throttled"] >= 1
    assert len(runner.agent.launches_of("app-0-main")) == 2
    assert scheduler._suppressed

    clock[0] = 101.0  # refill window passed
    runner.run([AdvanceCycles(2)])
    assert scheduler.metrics.counters()["revives"] == 2
    assert len(runner.agent.launches_of("app-0-main")) == 3


def test_run_forever_stops_after_consecutive_failures():
    """A permanently-failing cycle must stop the loop and record a
    fatal error instead of looping silently forever."""
    runner = ServiceTestRunner(ONE_POD_YAML)
    scheduler = runner.build().scheduler

    calls = []

    def broken_cycle(allow_footprint_growth=True):
        calls.append(1)
        raise RuntimeError("wedged")

    scheduler.run_cycle = broken_cycle
    thread = scheduler.run_forever(
        interval_s=0.01, max_consecutive_failures=3
    )
    thread.join(timeout=5.0)
    assert not thread.is_alive()
    assert len(calls) == 3
    assert "wedged" in scheduler.fatal_error


def test_health_endpoint_reports_fatal_error():
    import json
    import urllib.request

    from dcos_commons_tpu.http import ApiServer

    runner = ServiceTestRunner(ONE_POD_YAML)
    scheduler = runner.build().scheduler
    scheduler._fatal_error = "RuntimeError('wedged')"
    server = ApiServer(scheduler).start()
    try:
        try:
            with urllib.request.urlopen(server.url + "/v1/health") as resp:
                raise AssertionError("expected 503")
        except urllib.error.HTTPError as err:
            assert err.code == 503
            body = json.loads(err.read().decode())
            assert body["fatal_error"] == "RuntimeError('wedged')"
            assert not body["healthy"]
    finally:
        server.stop()


def test_multi_wedged_service_flags_fatal_and_health_503():
    """A service that fails every cycle in multi mode must trip
    fatal_error (for supervised restart) and turn aggregate
    /v1/health 503 — not loop silently forever."""
    import json
    import urllib.error
    import urllib.request

    from dcos_commons_tpu.http import ApiServer
    from dcos_commons_tpu.multi import MultiServiceScheduler
    from dcos_commons_tpu.offer.inventory import SliceInventory, TpuHost
    from dcos_commons_tpu.scheduler import SchedulerConfig
    from dcos_commons_tpu.specification.yaml_spec import from_yaml
    from dcos_commons_tpu.storage import MemPersister
    from dcos_commons_tpu.testing import FakeAgent

    multi = MultiServiceScheduler(
        persister=MemPersister(),
        inventory=SliceInventory([TpuHost(host_id="h0")]),
        agent=FakeAgent(),
        scheduler_config=SchedulerConfig(backoff_enabled=False),
    )
    multi.add_service(from_yaml(ONE_POD_YAML))
    broken = multi.get_service("throttle-svc")

    def boom(*a, **k):
        raise RuntimeError("store corrupted")

    broken.run_cycle = boom
    thread = multi.run_forever(interval_s=0.01)
    thread.join(timeout=5.0)
    assert not thread.is_alive()
    assert "store corrupted" in multi.fatal_error

    server = ApiServer(multi=multi).start()
    try:
        try:
            urllib.request.urlopen(server.url + "/v1/health")
            raise AssertionError("expected 503")
        except urllib.error.HTTPError as err:
            assert err.code == 503
            body = json.loads(err.read().decode())
            assert "store corrupted" in body["fatal_error"]
    finally:
        server.stop()


def test_unappliable_rlimit_fails_launch_with_error_status(tmp_path):
    """A setrlimit failure in the pure-Python preexec path surfaces as
    ValueError in the parent (CPython re-raises child errno as the
    builtin type): it must fail THE LAUNCH with an ERROR status, not
    escape into the scheduler loop (advisor follow-up; the native
    path's _exit(72) contract, mirrored)."""
    import time as _time

    from dcos_commons_tpu.agent.local import LocalProcessAgent
    from dcos_commons_tpu.common import TaskInfo, TaskState

    agent = LocalProcessAgent(str(tmp_path), use_native=False)
    try:
        agent.launch_one(
            TaskInfo(name="p-0-t", task_id="tid-bad-rlimit",
                     agent_id="h0", command="echo never-runs"),
            # soft > hard is rejected by setrlimit itself -> ValueError
            rlimits=[{"name": "RLIMIT_NOFILE", "soft": 100, "hard": 50}],
        )
        deadline = _time.monotonic() + 10
        statuses = []
        while _time.monotonic() < deadline:
            statuses = [
                s for s in agent.poll()
                if s.task_id == "tid-bad-rlimit"
            ]
            if statuses:
                break
            _time.sleep(0.05)
        assert statuses, "no status surfaced for the failed launch"
        assert statuses[0].state is TaskState.ERROR
        assert "launch failed" in statuses[0].message
    finally:
        agent.shutdown()
