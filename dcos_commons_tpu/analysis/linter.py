"""sdklint core: findings, suppressions, and the file walker.

The shape mirrors the build-gate's inline AST lint
(tests/test_build_gate.py) but as a library: each rule is a small
class with an id and docstring (the rule catalog renders from these),
findings carry a stable fingerprint so a repo-level baseline file can
track pre-existing debt, and ``# sdklint: disable=<rule>`` on (or
immediately above) the offending line suppresses a finding the way
the reference's ``@SuppressWarnings`` / checkstyle-off comments do.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set

# comment grammar, valid in .py and .yml alike:
#   # sdklint: disable=rule-a,rule-b     (this line / the line below)
#   # sdklint: disable-file=rule-a       (anywhere: whole file)
# "all" disables every rule.  The marker may share a comment with
# other tooling ("# noqa: BLE001, sdklint: disable=...").
# the rule list ends at a second '#', EOL, or a rationale separator:
# em-dash, '--', or a lone ' - ' (rule ids contain hyphens only
# WITHOUT surrounding whitespace, so '- ' is unambiguous)
_SUPPRESS_RE = re.compile(
    r"#.*?\bsdklint:\s*disable(?P<scope>-file)?\s*=\s*"
    r"(?P<rules>[A-Za-z0-9_,\-\s]+?)\s*(?:#|$|—|--|-\s)"
)


@dataclass
class Finding:
    """One rule violation at one source location."""

    file: str          # repo-relative posix path
    line: int          # 1-based
    rule: str          # rule id
    message: str

    @property
    def fingerprint(self) -> str:
        """Line-number-free identity used by the baseline file, so
        unrelated edits above a baselined finding don't resurface it."""
        return f"{self.file}::{self.rule}"

    def render(self) -> str:
        return f"{self.file}:{self.line}: [{self.rule}] {self.message}"

    def to_dict(self) -> Dict[str, object]:
        """Machine form for the CLI's ``--json`` output."""
        return {
            "file": self.file,
            "line": self.line,
            "rule": self.rule,
            "message": self.message,
        }


class LintContext:
    """Everything a rule needs about one source file."""

    def __init__(self, path: str, rel: str, source: str):
        self.path = path
        self.rel = rel.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree: Optional[ast.AST] = None
        try:
            self.tree = ast.parse(source, filename=path)
        except SyntaxError:
            # the build gate (py_compile) owns syntax errors; lint
            # rules simply don't run on an unparseable file
            self.tree = None

    def finding(self, node_or_line, rule_id: str, message: str) -> Finding:
        line = getattr(node_or_line, "lineno", node_or_line)
        return Finding(self.rel, int(line), rule_id, message)


class Suppressions:
    """The parsed suppression comments of ONE file — build once per
    file, query per finding (a per-finding re-scan would be
    O(findings x lines))."""

    def __init__(self, lines: Sequence[str]):
        self.per_line: Dict[int, Set[str]] = {}
        self.whole_file: Set[str] = set()
        for i, text in enumerate(lines, start=1):
            match = _SUPPRESS_RE.search(text)
            if not match:
                continue
            rules = {
                r.strip()
                for r in match.group("rules").split(",") if r.strip()
            }
            if match.group("scope"):
                self.whole_file |= rules
            else:
                self.per_line.setdefault(i, set()).update(rules)

    def covers(self, finding: Finding) -> bool:
        if "all" in self.whole_file or finding.rule in self.whole_file:
            return True
        for lineno in (finding.line, finding.line - 1):
            rules = self.per_line.get(lineno, ())
            if "all" in rules or finding.rule in rules:
                return True
        return False


def is_suppressed(finding: Finding, lines: Sequence[str]) -> bool:
    return Suppressions(lines).covers(finding)


@dataclass
class LintResult:
    findings: List[Finding] = field(default_factory=list)
    suppressed: List[Finding] = field(default_factory=list)
    files_checked: int = 0

    def render(self) -> str:
        return "\n".join(f.render() for f in self.findings)


def _walk_py_files(root: str, subdirs: Iterable[str]) -> List[str]:
    out = []
    for sub in subdirs:
        top = os.path.join(root, sub)
        for dirpath, dirs, files in os.walk(top):
            dirs[:] = sorted(d for d in dirs if d != "__pycache__")
            out += [
                os.path.join(dirpath, f)
                for f in sorted(files)
                if f.endswith(".py")
            ]
    return out


def lint_paths(
    paths: Iterable[str],
    root: str,
    rules: Optional[Sequence] = None,
) -> LintResult:
    from dcos_commons_tpu.analysis.rules import all_rules

    result = LintResult()
    active = list(rules) if rules is not None else all_rules()
    for path in paths:
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
        ctx = LintContext(path, os.path.relpath(path, root), source)
        suppressions = Suppressions(ctx.lines)
        result.files_checked += 1
        for rule in active:
            if not rule.applies_to(ctx):
                continue
            for finding in rule.check(ctx):
                if suppressions.covers(finding):
                    result.suppressed.append(finding)
                else:
                    result.findings.append(finding)
    result.findings.sort(key=lambda f: (f.file, f.line, f.rule))
    return result


def lint_tree(
    root: str,
    subdirs: Sequence[str] = ("dcos_commons_tpu", "frameworks"),
    rules: Optional[Sequence] = None,
) -> LintResult:
    """Lint every .py file under ``root``'s ``subdirs`` (the library
    and the packaged frameworks; tests are the build gate's problem)."""
    return lint_paths(_walk_py_files(root, subdirs), root, rules)
