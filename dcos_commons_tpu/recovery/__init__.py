"""L2: failure recovery — synthesized plans for crashed/lost tasks.

Reference: sdk/scheduler/.../scheduler/recovery/ —
DefaultRecoveryPlanManager.java:53,142,164,378-420 (plan synthesized
on the fly from failed tasks; escalation TRANSIENT -> PERMANENT),
RecoveryType.java:7-25, monitor/ (NeverFailureMonitor,
TimedFailureMonitor.java:20-60, TestingFailureMonitor),
FailureUtils (permanently-failed task labels),
RecoveryPlanOverrider hook (CassandraRecoveryPlanOverrider.java:38).

TPU mapping (SURVEY.md section 5.3): preemption/maintenance events play
TASK_LOST; PERMANENT recovery of a gang pod = re-place the sub-slice
and restart from checkpoint; one lost worker flips the WHOLE gang to
recovery (the pjit mesh cannot run degraded).
"""

from dcos_commons_tpu.recovery.monitor import (
    FailureMonitor,
    NeverFailureMonitor,
    TestingFailureMonitor,
    TimedFailureMonitor,
)
from dcos_commons_tpu.recovery.manager import (
    DefaultRecoveryPlanManager,
    RecoveryPlanOverrider,
)

__all__ = [
    "DefaultRecoveryPlanManager",
    "FailureMonitor",
    "NeverFailureMonitor",
    "RecoveryPlanOverrider",
    "TestingFailureMonitor",
    "TimedFailureMonitor",
]
