"""Integration-test harness: drive a SERVED scheduler over HTTP.

Reference: testing/sdk_plan.py:29-333 (wait_for_completed_deployment,
wait_for_plan_status, force_complete), testing/sdk_tasks.py (task-id
snapshots asserting which tasks restarted across an operation), and
testing/sdk_install.py (process launch + teardown).  Where the
reference drives a real DC/OS cluster through the dcos CLI, this
drives real scheduler/agent *processes* through their HTTP APIs —
everything crosses sockets, nothing is in-process.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional

from dcos_commons_tpu.cli.client import ApiClient, CliError


class WaitTimeout(AssertionError):
    pass


def wait_for(predicate, timeout_s: float = 30.0, interval_s: float = 0.1,
             what: str = "condition"):
    """Poll until ``predicate()`` is truthy; returns its value."""
    deadline = time.monotonic() + timeout_s
    last_error: Optional[Exception] = None
    while time.monotonic() < deadline:
        try:
            value = predicate()
            if value:
                return value
            last_error = None
        except (CliError, OSError) as e:
            last_error = e
        time.sleep(interval_s)
    detail = f" (last error: {last_error})" if last_error else ""
    raise WaitTimeout(f"timed out after {timeout_s}s waiting for {what}{detail}")


class ServiceClient(ApiClient):
    """sdk_plan + sdk_tasks vocabulary over one served scheduler."""

    # -- sdk_plan analogues ------------------------------------------

    def plan_status(self, plan: str) -> str:
        return self.get(f"/v1/plans/{plan}")["status"]

    def wait_for_plan_status(
        self, plan: str, status: str = "COMPLETE", timeout_s: float = 60.0
    ) -> dict:
        def check():
            body = self.get(f"/v1/plans/{plan}")
            return body if body["status"] == status else None

        return wait_for(
            check, timeout_s, what=f"plan {plan} to reach {status}"
        )

    def wait_for_completed_deployment(self, timeout_s: float = 60.0) -> dict:
        plans = wait_for(
            lambda: self.get("/v1/plans"), timeout_s, what="plan list"
        )
        plan = "update" if "update" in plans else "deploy"
        return self.wait_for_plan_status(plan, "COMPLETE", timeout_s)

    def wait_for_completed_recovery(self, timeout_s: float = 60.0) -> dict:
        return self.wait_for_plan_status("recovery", "COMPLETE", timeout_s)

    def force_complete(self, plan: str, phase: str, step: str) -> None:
        self.post(
            f"/v1/plans/{plan}/forceComplete",
            {"phase": phase, "step": step},
        )

    # -- sdk_tasks analogues -----------------------------------------

    def task_ids(self, prefix: str = "") -> Dict[str, str]:
        """Snapshot of task name -> live task id (sdk_tasks.get_task_ids)."""
        out: Dict[str, str] = {}
        for pod in self.get("/v1/pod/status")["pods"]:
            for instance in pod["instances"]:
                for task in instance["tasks"]:
                    if task["id"] and task["name"].startswith(prefix):
                        out[task["name"]] = task["id"]
        return out

    def wait_for_tasks_updated(
        self, old_ids: Dict[str, str], prefix: str = "",
        timeout_s: float = 60.0,
    ) -> Dict[str, str]:
        """Every task under ``prefix`` must have a NEW id and be running
        (sdk_tasks.check_tasks_updated)."""
        def check():
            now = self.task_ids(prefix)
            relevant = {n: i for n, i in old_ids.items()
                        if n.startswith(prefix)}
            if not now or set(now) < set(relevant):
                return None
            changed = all(
                now.get(name) and now[name] != old_id
                for name, old_id in relevant.items()
            )
            return now if changed else None

        return wait_for(
            check, timeout_s, what=f"tasks {prefix or '*'} to be replaced"
        )

    def check_tasks_not_updated(
        self, old_ids: Dict[str, str], prefix: str = ""
    ) -> None:
        now = self.task_ids(prefix)
        for name, old_id in old_ids.items():
            if not name.startswith(prefix):
                continue
            assert now.get(name) == old_id, (
                f"task {name} restarted: {old_id} -> {now.get(name)}"
            )

    def wait_for_task_state(
        self, task_name: str, state: str, timeout_s: float = 60.0
    ) -> None:
        def check():
            for pod in self.get("/v1/pod/status")["pods"]:
                for instance in pod["instances"]:
                    for task in instance["tasks"]:
                        if task["name"] == task_name and \
                                task["status"] == state:
                            return True
            return None

        wait_for(check, timeout_s, what=f"{task_name} to reach {state}")


# ---------------------------------------------------------------------------
# Process harness: launch real scheduler + agent processes
# ---------------------------------------------------------------------------


def _read_announce(path: str, timeout_s: float = 20.0) -> str:
    def check():
        if os.path.exists(path):
            with open(path) as f:
                content = f.read().strip()
            return content or None
        return None

    return wait_for(check, timeout_s, what=f"announce file {path}")


class AgentProcess:
    """One agent daemon subprocess (a simulated TPU-VM host)."""

    def __init__(self, host_id: str, workdir: str, repo_root: str = "",
                 extra_args: Optional[List[str]] = None):
        self.host_id = host_id
        self.workdir = workdir
        announce = os.path.join(workdir, "announce")
        os.makedirs(workdir, exist_ok=True)
        if os.path.exists(announce):
            os.remove(announce)  # never read a previous run's port
        self._log = open(os.path.join(workdir, "agent.log"), "ab")
        self.process = subprocess.Popen(
            [
                sys.executable, "-m", "dcos_commons_tpu", "agent",
                "--host-id", host_id,
                "--workdir", os.path.join(workdir, "sandboxes"),
                "--announce-file", announce,
                *(extra_args or []),
            ],
            cwd=repo_root or None,
            stdout=self._log,
            stderr=subprocess.STDOUT,
        )
        announced = _read_announce(announce)
        self.url = announced.split()[-1]

    def kill(self) -> None:
        """Hard-kill the daemon — the host-failure injection."""
        self.process.kill()
        self.process.wait(timeout=10)
        self._log.close()

    def stop(self) -> None:
        if self.process.poll() is None:
            self.process.terminate()
            try:
                self.process.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self.process.kill()
                self.process.wait(timeout=10)
        if not self._log.closed:
            self._log.close()


def start_state_server(workdir: str, repo_root: str = "",
                       standby_of: str = ""):
    """Spawn a ``state-server`` subprocess; returns (proc, state_url,
    log_file).  Caller terminates the proc and closes the log.
    ``standby_of`` runs it as a hot standby of that primary URL."""
    announce = os.path.join(workdir, "state-announce")
    os.makedirs(workdir, exist_ok=True)
    if os.path.exists(announce):
        os.remove(announce)
    log = open(os.path.join(workdir, "state-server.log"), "ab")
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "dcos_commons_tpu", "state-server",
            "--data-dir", os.path.join(workdir, "data"),
            "--announce-file", announce,
            *(("--standby-of", standby_of) if standby_of else ()),
        ],
        cwd=repo_root or None,
        stdout=log,
        stderr=subprocess.STDOUT,
    )
    url = _read_announce(announce)
    return proc, url, log


def promote_state_server(standby_url: str, fence_old: str = "",
                         repo_root: str = "") -> None:
    """Operator failover verb: promote the standby at ``standby_url``
    to primary (``state-server --promote``); optionally demote a
    still-reachable old primary."""
    subprocess.run(
        [
            sys.executable, "-m", "dcos_commons_tpu", "state-server",
            "--promote", standby_url,
            *(("--fence-old", fence_old) if fence_old else ()),
        ],
        cwd=repo_root or None,
        check=True,
        capture_output=True,
        timeout=30,
    )


def reap_orphan_tasks(agents) -> None:
    """Kill task process groups that outlive their daemons.  Stopping
    (or killing) a daemon leaves its supervised tasks RUNNING by
    design — durable-task semantics — so tests that launch real
    long-running commands must reap them or leak processes into the
    host.  Pids come from the supervisors' durable records."""
    for agent in agents:
        root = os.path.join(agent.workdir, "sandboxes")
        for dirpath, _dirs, files in os.walk(root):
            for name in ("child.pid", "task.pid"):
                if name not in files:
                    continue
                try:
                    pid = int(open(os.path.join(dirpath, name)).read())
                    os.killpg(pid, signal.SIGKILL)
                except (OSError, ValueError):
                    pass


class SchedulerProcess:
    """One served scheduler subprocess (``dcos_commons_tpu serve``)."""

    def __init__(
        self,
        svc_yml: str,
        topology_yml: str,
        workdir: str,
        env: Optional[Dict[str, str]] = None,
        repo_root: str = "",
        wait_listening: bool = True,
        extra_args: Optional[List[str]] = None,
        auth_token: str = "",
        ca_file: str = "",
    ):
        self.auth_token = auth_token
        self.ca_file = ca_file
        self.workdir = workdir
        self._svc_yml = svc_yml
        self._topology_yml = topology_yml
        self._env = dict(env or {})
        self._repo_root = repo_root
        self._extra_args = list(extra_args or [])
        announce = os.path.join(workdir, "announce")
        os.makedirs(workdir, exist_ok=True)
        if os.path.exists(announce):
            os.remove(announce)  # never read a previous run's port
        run_env = dict(os.environ)
        run_env.update(env or {})
        self._log = open(os.path.join(workdir, "scheduler.log"), "ab")
        self.process = subprocess.Popen(
            [
                sys.executable, "-m", "dcos_commons_tpu", "serve",
                svc_yml,
                "--topology", topology_yml,
                "--port", "0",
                "--state-dir", os.path.join(workdir, "state"),
                "--sandbox-root", os.path.join(workdir, "sandboxes"),
                "--announce-file", announce,
                *(extra_args or []),
            ],
            cwd=repo_root or None,
            env=run_env,
            stdout=self._log,
            stderr=subprocess.STDOUT,
        )
        self.url = _read_announce(announce) if wait_listening else ""

    def client(self) -> ServiceClient:
        return ServiceClient(
            self.url, auth_token=self.auth_token, ca_file=self.ca_file
        )

    def terminate(self) -> int:
        if self.process.poll() is None:
            self.process.terminate()
        try:
            return self.process.wait(timeout=15)
        except subprocess.TimeoutExpired:
            self.process.kill()
            return self.process.wait(timeout=10)
        finally:
            if not self._log.closed:
                self._log.close()

    def upgrade(
        self,
        svc_yml: Optional[str] = None,
        env: Optional[Dict[str, str]] = None,
        timeout_s: float = 90.0,
    ) -> "SchedulerProcess":
        """The sdk_upgrade analogue: stop this scheduler, start a new
        one over the SAME state with a changed service definition or
        env, and wait for the resulting update plan to complete.

        Returns the new SchedulerProcess (self is terminated).
        Reference: testing/sdk_upgrade.py — bump the package/options,
        wait_for_completed_deployment."""
        assert self.terminate() == 0, self.log_tail()
        successor = SchedulerProcess(
            svc_yml or self._svc_yml,
            self._topology_yml,
            self.workdir,
            env={**(self._env or {}), **(env or {})},
            repo_root=self._repo_root,
            extra_args=self._extra_args,
            auth_token=self.auth_token,
            ca_file=self.ca_file,
        )
        client = successor.client()

        def rolled_out():
            # rollout after a completed deployment is the 'update' plan
            for plan in ("update", "deploy"):
                try:
                    if client.plan_status(plan) == "COMPLETE":
                        return True
                except CliError:
                    continue
            return None

        wait_for(rolled_out, timeout_s, what="post-upgrade rollout")
        return successor

    def log_tail(self, lines: int = 40) -> str:
        path = os.path.join(self.workdir, "scheduler.log")
        if not os.path.exists(path):
            return ""
        with open(path, errors="replace") as f:
            return "\n".join(f.read().splitlines()[-lines:])
