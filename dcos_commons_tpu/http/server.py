"""Threaded HTTP server mapping /v1 routes onto SchedulerApi.

Reference: framework/ApiServer.java — the Jetty server started before
offers are accepted (FrameworkRunner.java:130-138).  Stdlib-only:
ThreadingHTTPServer + a small regex router; JSON in/out.
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from dcos_commons_tpu.http.api import SchedulerApi

Route = Tuple[str, re.Pattern, Callable, bool]


def compile_route(method: str, pattern: str, handler: Callable,
                  wants_body: bool = False) -> Route:
    """The one anchoring rule for every route, built-in or custom.
    ``wants_body`` handlers receive a third argument: the request's
    parsed JSON body (``{}`` when absent/invalid)."""
    return (method, re.compile(f"^{pattern}$"), handler, wants_body)


def build_routes(api: SchedulerApi) -> List[Route]:
    r = compile_route

    # handlers receive (match, query) and return (code, body)
    return [
        r("GET", r"/v1/health", lambda m, q: api.health()),
        # plans (verbs accept ?phase= & ?step=, as the reference's POST
        # bodies/params do — PlansQueries.java:47-231)
        r("GET", r"/v1/plans", lambda m, q: api.list_plans()),
        r("GET", r"/v1/plans/([^/]+)", lambda m, q: api.get_plan(m.group(1))),
        r("POST", r"/v1/plans/([^/]+)/interrupt",
          lambda m, q: api.plan_interrupt(m.group(1), _one(q, "phase"),
                                          _one(q, "step"))),
        r("POST", r"/v1/plans/([^/]+)/continue",
          lambda m, q: api.plan_continue(m.group(1), _one(q, "phase"),
                                         _one(q, "step"))),
        r("POST", r"/v1/plans/([^/]+)/forceComplete",
          lambda m, q: api.plan_force_complete(m.group(1), _one(q, "phase"),
                                               _one(q, "step"))),
        r("POST", r"/v1/plans/([^/]+)/restart",
          lambda m, q: api.plan_restart(m.group(1), _one(q, "phase"),
                                        _one(q, "step"))),
        r("POST", r"/v1/plans/([^/]+)/start",
          lambda m, q, body: api.plan_start(m.group(1), body.get("env")),
          True),
        r("POST", r"/v1/plans/([^/]+)/stop",
          lambda m, q: api.plan_stop(m.group(1))),
        # pods
        r("GET", r"/v1/pod", lambda m, q: api.list_pods()),
        r("GET", r"/v1/pod/status", lambda m, q: api.pod_statuses()),
        r("GET", r"/v1/pod/([^/]+)/status",
          lambda m, q: api.pod_status(m.group(1))),
        r("GET", r"/v1/pod/([^/]+)/info",
          lambda m, q: api.pod_info(m.group(1))),
        r("POST", r"/v1/pod/([^/]+)/restart",
          lambda m, q: api.pod_restart(m.group(1))),
        r("POST", r"/v1/pod/([^/]+)/replace",
          lambda m, q: api.pod_replace(m.group(1))),
        r("POST", r"/v1/pod/([^/]+)/pause",
          lambda m, q: api.pod_pause(m.group(1), q.get("task"))),
        r("POST", r"/v1/pod/([^/]+)/resume",
          lambda m, q: api.pod_resume(m.group(1), q.get("task"))),
        # manual scale (ISSUE 15): {"count": N} — rides the autoscale
        # plan machinery, honoring the single-flight rule; /abandon
        # drops an in-flight action, settling the count to deployed
        # reality
        r("POST", r"/v1/pod/([^/]+)/scale/abandon",
          lambda m, q: api.pod_scale_abandon(m.group(1))),
        r("POST", r"/v1/pod/([^/]+)/scale",
          lambda m, q, body: api.pod_scale(m.group(1), body), True),
        # configs
        r("GET", r"/v1/configs", lambda m, q: api.list_configs()),
        r("GET", r"/v1/configs/targetId", lambda m, q: api.target_config_id()),
        r("GET", r"/v1/configs/target", lambda m, q: api.target_config()),
        r("GET", r"/v1/configs/([^/]+)",
          lambda m, q: api.get_config(m.group(1))),
        # state
        r("GET", r"/v1/state/properties",
          lambda m, q: api.state_properties()),
        r("GET", r"/v1/state/properties/([^/]+)",
          lambda m, q: api.state_property(m.group(1))),
        r("GET", r"/v1/state/frameworkId",
          lambda m, q: api.state_framework_id()),
        r("GET", r"/v1/state/zones", lambda m, q: api.state_zones()),
        # operator files in the state store (StateQueries.java:78)
        r("GET", r"/v1/state/files", lambda m, q: api.state_files()),
        r("GET", r"/v1/state/files/([^/]+)",
          lambda m, q: api.state_file_get(m.group(1))),
        r("PUT", r"/v1/state/files/([^/]+)",
          lambda m, q, body: api.state_file_put(m.group(1), body),
          True),
        # hosts: preemption & maintenance lifecycle (ISSUE 13) — the
        # drain verb excludes the host from placement and flips its
        # serve backends to draining BEFORE anything is killed;
        # preempt surfaces an involuntary capacity loss (tasks LOST,
        # gang recovery synthesized); up returns the host to service
        r("GET", r"/v1/hosts", lambda m, q: api.list_hosts()),
        r("POST", r"/v1/hosts/([^/]+)/drain",
          lambda m, q, body: api.host_drain(m.group(1), body), True),
        r("POST", r"/v1/hosts/([^/]+)/preempt",
          lambda m, q: api.host_preempt(m.group(1))),
        r("POST", r"/v1/hosts/([^/]+)/up",
          lambda m, q: api.host_up(m.group(1))),
        # endpoints
        r("GET", r"/v1/endpoints", lambda m, q: api.list_endpoints()),
        r("GET", r"/v1/endpoints/([^/]+)",
          lambda m, q: api.get_endpoint(m.group(1))),
        # artifacts
        r("GET", r"/v1/artifacts/template/([^/]+)/([^/]+)/([^/]+)/([^/]+)",
          lambda m, q: api.artifact_template(
              m.group(1), m.group(2), m.group(3), m.group(4))),
        # debug
        r("GET", r"/v1/debug/offers", lambda m, q: api.debug_offers()),
        r("GET", r"/v1/debug/plans", lambda m, q: api.debug_plans()),
        r("GET", r"/v1/debug/taskStatuses",
          lambda m, q: api.debug_task_statuses()),
        r("GET", r"/v1/debug/reservations",
          lambda m, q: api.debug_reservations()),
        # traceview: text timeline, or ?fmt=chrome for Perfetto
        r("GET", r"/v1/debug/trace",
          lambda m, q: api.debug_trace(_one(q, "fmt"))),
        # HA: leader lease, fencing epoch, standby watermarks, the
        # last re-hydration report (the failover runbook's dashboard)
        r("GET", r"/v1/debug/ha",
          lambda m, q: api.debug_ha()),
        # serving load: per-pod slot-engine gauges (queue depth,
        # active slots, KV occupancy, tokens/s) merged from sandboxes
        r("GET", r"/v1/debug/serving",
          lambda m, q: api.debug_serving()),
        # serving front door: per-router gauge snapshots (pod set,
        # affinity hit rate, failovers) + the endpoint generation
        r("GET", r"/v1/debug/router",
          lambda m, q: api.debug_router()),
        # fleet health plane: detector states, suspect hosts, metric
        # history (?metric=<name> for one full series)
        r("GET", r"/v1/debug/health",
          lambda m, q: api.debug_health(_one(q, "metric"))),
        # durable event journal (?since=<seq> cursor, ?kind= filter)
        r("GET", r"/v1/debug/events",
          lambda m, q: api.debug_events(_one(q, "since"),
                                        _one(q, "kind"))),
        # metrics
        r("GET", r"/v1/metrics/prometheus",
          lambda m, q: api.metrics_prometheus()),
        r("GET", r"/v1/metrics", lambda m, q: api.metrics_json()),
    ]


def _one(query: dict, key: str) -> Optional[str]:
    values = query.get(key)
    return values[0] if values else None


def _multi_health(multi) -> tuple:
    """Aggregate /v1/health for multi-service mode: unhealthy when the
    multi loop flagged fatal or any service's plans carry errors."""
    fatal = getattr(multi, "fatal_error", None)
    services = {}
    has_errors = False
    for name, svc in multi.services().items():
        plans = svc.plans()
        errors = any(p.has_errors() for p in plans.values())
        has_errors = has_errors or errors
        services[name] = {
            "plans": {n: p.get_status().value for n, p in plans.items()},
            "errors": errors,
        }
    healthy = fatal is None and not has_errors
    body = {"healthy": healthy, "services": services}
    if fatal is not None:
        body["fatal_error"] = fatal
    return (200 if healthy else 503), body


class ApiServer:
    """Reference: framework/ApiServer.java — started before the event
    loop accepts work; ``port=0`` binds an ephemeral port (tests).

    Multi-service mode (``multi=``): /v1/multi lists/adds/removes
    services, and /v1/multi/<name>/v1/... routes any single-service
    path to that service (reference: http/endpoints/Multi*.java route
    per-service by name)."""

    def __init__(self, scheduler=None, port: int = 0, host: str = "127.0.0.1",
                 multi=None, extra_routes=None, auth_token: str = "",
                 tls=None):
        # cluster bearer token (security/auth.py): when set, every
        # route but /v1/health requires Authorization — the reference
        # fronts its API with admin-router auth; tls=(cert, key) files
        # serve HTTPS issued by the in-repo CA
        from dcos_commons_tpu.security import auth as _auth
        # frameworks may register CUSTOM endpoints (reference:
        # Cassandra's SeedsResource, wired in each Main.java):
        # extra_routes is [(method, pattern, handler(match, query))],
        # compiled like the built-ins and matched FIRST
        routes = [compile_route(*entry) for entry in (extra_routes or [])]
        # the api object is long-lived and swappable: a live options
        # update (POST /v1/update) rebuilds the scheduler in-process
        # and repoints this server at it via set_scheduler(); custom
        # routes (which close over the scheduler) are refreshed via
        # set_extra_routes at the same time
        self.api = SchedulerApi(scheduler) if scheduler else None
        routes += build_routes(self.api) if self.api else []
        self._routes = routes
        self._extra_count = len(extra_routes or [])
        multi_scheduler = multi

        class Handler(BaseHTTPRequestHandler):
            # quiet request logging (structured logs belong to the app)
            def log_message(self, fmt, *args):
                pass

            def _dispatch(self, method: str) -> None:
                parsed = urlparse(self.path)
                query = parse_qs(parsed.query)
                if parsed.path != "/v1/health" and not _auth.check_bearer(
                    self.headers, auth_token
                ):
                    self._reply(*_auth.UNAUTHORIZED)
                    return
                if multi_scheduler is not None and \
                        parsed.path.startswith("/v1/multi"):
                    code, body = self._dispatch_multi(
                        method, parsed.path, query
                    )
                    self._reply(code, body)
                    return
                # snapshot: set_extra_routes may splice concurrently
                for route_method, pattern, handler, wants_body in list(routes):
                    if route_method != method:
                        continue
                    match = pattern.match(parsed.path)
                    if match is None:
                        continue
                    try:
                        if wants_body:
                            code, body = handler(match, query,
                                                 self._json_body())
                        else:
                            code, body = handler(match, query)
                    except Exception as e:  # surface, don't kill the server
                        code, body = 500, {"message": f"internal error: {e}"}
                    self._reply(code, body)
                    return
                if multi_scheduler is not None and method == "GET" and \
                        parsed.path == "/v1/health":
                    # aggregate health in multi-only mode (per-service
                    # health is /v1/multi/<name>/v1/health)
                    self._reply(*_multi_health(multi_scheduler))
                    return
                self._reply(404, {"message": f"no route {method} {parsed.path}"})

            def _dispatch_multi(self, method: str, path: str, query):
                rest = path[len("/v1/multi"):].strip("/")
                if not rest:
                    if method == "GET":
                        return 200, multi_scheduler.service_names()
                    return 405, {"message": "use GET /v1/multi"}
                if rest == "events" and method == "GET":
                    # the fleet-level event journal (admission
                    # rejections, service add/uninstall); per-service
                    # journals live at /v1/multi/<name>/v1/debug/events
                    journal = getattr(multi_scheduler, "journal", None)
                    if journal is None:
                        return 200, {"events": [], "seq": 0}
                    try:
                        since = int((query.get("since") or ["0"])[0])
                    except ValueError:
                        return 400, {"message": "bad since cursor"}
                    return 200, {
                        "events": journal.events(since=since),
                        "seq": journal.last_seq,
                        "journal": journal.describe(),
                    }
                if rest == "hosts" and method == "GET":
                    # fleet host states (the shared inventory)
                    inv = getattr(multi_scheduler, "inventory", None)
                    if inv is None or not hasattr(inv, "host_states"):
                        return 200, {"hosts": {}}
                    return 200, {"hosts": inv.host_states()}
                if rest.startswith("hosts/") and method == "POST":
                    # fleet-level host lifecycle: one inventory mark,
                    # preemption stamping fanned out to every service
                    parts = rest.split("/")
                    if len(parts) == 3:
                        _, host_id, verb = parts
                        try:
                            if verb == "drain":
                                body = self._json_body()
                                window_s = float(
                                    body.get("window_s", 0) or 0
                                )
                                changed = multi_scheduler.drain_host(
                                    host_id, window_s=window_s
                                )
                                return 200, {
                                    "host": host_id,
                                    "state": "maintenance",
                                    "changed": changed,
                                }
                            if verb == "preempt":
                                lost = multi_scheduler.preempt_host(
                                    host_id
                                )
                                return 200, {
                                    "host": host_id,
                                    "state": "preempted",
                                    "tasks_lost": lost,
                                }
                            if verb == "up":
                                changed = multi_scheduler.undrain_host(
                                    host_id
                                )
                                return 200, {
                                    "host": host_id,
                                    "state": "up",
                                    "changed": changed,
                                }
                        except KeyError:
                            return 404, {
                                "message": f"no host {host_id}"
                            }
                        except (TypeError, ValueError) as e:
                            return 400, {"message": str(e)}
                    return 404, {
                        "message": f"no route {method} /v1/multi/{rest}"
                    }
                name, _, sub = rest.partition("/")
                if name in ("events", "hosts") and method == "PUT" \
                        and not sub:
                    # reserved: GET /v1/multi/events is the fleet
                    # journal and /v1/multi/hosts the fleet host
                    # surface — a service deployed under either name
                    # would have its bare-name routes shadowed
                    return 400, {
                        "message": f"service name {name!r} is reserved "
                                   "(fleet route)"
                    }
                if method == "PUT" and not sub:
                    # body: service YAML, or a framework package
                    # tarball (Content-Type: application/gzip — the
                    # Cosmos install flow; reference: dynamic add via
                    # MultiServiceResource / ServiceStore).  With
                    # ?upgrade=true an existing service takes the new
                    # package version (Cosmos `update`): validated
                    # config diff -> rolling update over live state
                    length = int(self.headers.get("Content-Length", 0))
                    body = self.rfile.read(length)
                    ctype = self.headers.get("Content-Type", "")
                    upgrade = (query.get("upgrade") or ["false"])[0] \
                        .lower() in ("1", "true", "yes")
                    # operator options ride a header (the body is the
                    # tarball): base64 of the Cosmos-style options JSON
                    options = None
                    opts_header = self.headers.get("X-Service-Options")
                    if opts_header:
                        import base64 as _b64

                        try:
                            options = json.loads(
                                _b64.b64decode(opts_header)
                            )
                        except (ValueError, TypeError) as e:
                            return 400, {
                                "message": f"bad X-Service-Options: {e}"
                            }
                    from dcos_commons_tpu.multi.admission import (
                        AdmissionError,
                        validate_service_yaml,
                    )

                    try:
                        if "gzip" in ctype or body[:2] == b"\x1f\x8b":
                            multi_scheduler.install_package(
                                name, body, upgrade=upgrade,
                                options=options,
                            )
                            return 200, {
                                "message": f"package {name} "
                                f"{'upgraded' if upgrade else 'installed'}"
                            }
                        if options is not None:
                            # silently ignoring operator options would
                            # contradict the plane's whole point
                            return 400, {
                                "message": "options apply to package "
                                           "installs (gzip body) only",
                            }
                        # admission control: the CI analyzers run as
                        # production guardrails BEFORE ServiceStore
                        # persists anything; a rejected spec returns
                        # 422 with line-anchored findings, an admitted
                        # one is stored unchanged
                        spec, findings = validate_service_yaml(
                            body.decode("utf-8"), name,
                            inventory=getattr(
                                multi_scheduler, "inventory", None
                            ),
                        )
                        if findings:
                            raise AdmissionError(findings)
                        multi_scheduler.add_service(spec)
                    except AdmissionError as e:
                        # journal the rejection: the operator who
                        # PUT a bad spec is not always the operator
                        # who later asks "why did nothing deploy?"
                        journal = getattr(
                            multi_scheduler, "journal", None
                        )
                        if journal is not None:
                            journal.append(
                                "admission",
                                service=name,
                                findings=len(e.findings),
                                message=(
                                    f"spec for {name!r} rejected: "
                                    + "; ".join(
                                        f.message for f in e.findings[:3]
                                    )
                                ),
                            )
                            journal.flush()
                        return 422, {
                            "message": f"spec rejected by admission "
                                       f"control ({len(e.findings)} "
                                       "finding(s))",
                            "findings": [
                                f.to_dict() for f in e.findings
                            ],
                        }
                    except Exception as e:
                        return 400, {"message": str(e)}
                    return 200, {"message": f"service {name} added"}
                if method == "DELETE" and not sub:
                    try:
                        multi_scheduler.uninstall_service(name)
                    except KeyError:
                        return 404, {"message": f"no service {name}"}
                    return 200, {"message": f"service {name} uninstalling"}
                service = multi_scheduler.get_service(name)
                if service is None:
                    return 404, {"message": f"no service {name}"}
                sub_path = f"/{sub}" if sub.startswith("v1") else f"/v1/{sub}"
                sub_routes = build_routes(SchedulerApi(service))
                for route_method, pattern, handler, wants_body in sub_routes:
                    if route_method != method:
                        continue
                    match = pattern.match(sub_path)
                    if match is None:
                        continue
                    try:
                        if wants_body:
                            return handler(match, query, self._json_body())
                        return handler(match, query)
                    except Exception as e:
                        return 500, {"message": f"internal error: {e}"}
                return 404, {"message": f"no route {method} {sub_path}"}

            def _json_body(self) -> dict:
                length = int(self.headers.get("Content-Length", 0))
                if not length:
                    return {}
                raw = self.rfile.read(length)
                try:
                    parsed_body = json.loads(raw.decode("utf-8"))
                except (ValueError, UnicodeDecodeError):
                    return {}
                return parsed_body if isinstance(parsed_body, dict) else {}

            def _reply(self, code: int, body) -> None:
                if isinstance(body, str):
                    payload = body.encode("utf-8")
                    content_type = "text/plain; charset=utf-8"
                else:
                    payload = json.dumps(body, indent=2).encode("utf-8")
                    content_type = "application/json"
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):
                self._dispatch("GET")

            def do_POST(self):
                self._dispatch("POST")

            def do_PUT(self):
                self._dispatch("PUT")

            def do_DELETE(self):
                self._dispatch("DELETE")

        self._server = _auth.wrap_http_server(
            ThreadingHTTPServer((host, port), Handler), tls
        )
        self._scheme = _auth.url_scheme(tls)
        self._thread: Optional[threading.Thread] = None

    def set_scheduler(self, scheduler) -> None:
        """Repoint every route at a freshly-rebuilt scheduler (live
        config update — the process and its listening socket survive)."""
        if self.api is not None:
            self.api.set_scheduler(scheduler)

    def set_extra_routes(self, extra_routes) -> None:
        """Replace the CUSTOM route block (framework endpoints close
        over the scheduler object, so a live update must rebuild them
        too or they would keep serving the pre-update scheduler)."""
        compiled = [compile_route(*entry) for entry in extra_routes]
        self._routes[: self._extra_count] = compiled
        self._extra_count = len(compiled)

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    @property
    def url(self) -> str:
        host, port = self._server.server_address[:2]
        return f"{self._scheme}://{host}:{port}"

    def start(self) -> "ApiServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="api-server", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
