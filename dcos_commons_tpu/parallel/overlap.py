"""XLA collective/compute overlap flags for the training fast path.

The step-loop restructuring (ISSUE 7: `make_train_step` microbatched
gradient accumulation, donated buffers, the worker's bounded
in-flight window) gives XLA per-microbatch ICI/DCN collectives it
*can* overlap with the next microbatch's compute.  Whether it *does*
is governed by the latency-hiding scheduler: on several libtpu
builds the async-collective fusion passes default off, and a step
that could hide its reduce-scatters behind the backward pass instead
serializes them at the end (the megatron/alpa overlap discipline,
lost by default).

:func:`enable_collective_overlap` prepends the known-good flag set to
``XLA_FLAGS`` — BEFORE jax initializes its backend, which is why the
worker calls it first thing in ``main()``.  Rules of engagement:

* TPU-only: the flags are libtpu vocabulary; an XLA:CPU build treats
  unknown flags as fatal, so nothing is touched unless the
  scheduler's env contract says this is a TPU task
  (``TPU_GENERATION``) and ``JAX_PLATFORMS`` is not forcing cpu;
* the operator wins: a flag already spelled in ``XLA_FLAGS`` (either
  polarity) is never overridden — ours are PREPENDED and XLA lets the
  later spelling win;
* ``TRAIN_XLA_OVERLAP=0`` opts the whole set out (the same escape
  hatch family as ``TRAIN_INFLIGHT_STEPS=0``).
"""

from __future__ import annotations

import os
from typing import List, MutableMapping, Optional

# the latency-hiding scheduler set: fuse collectives with async
# start/done pairs and let the scheduler float compute between them
OVERLAP_FLAGS = (
    "--xla_tpu_enable_async_collective_fusion=true",
    "--xla_tpu_enable_async_collective_fusion_fuse_all_gather=true",
    "--xla_tpu_enable_async_collective_fusion_multiple_steps=true",
    "--xla_tpu_overlap_compute_collective_tc=true",
    "--xla_enable_async_all_gather=true",
    "--xla_enable_async_collective_permute=true",
)


def enable_collective_overlap(
    env: Optional[MutableMapping[str, str]] = None,
) -> List[str]:
    """Prepend the overlap flag set to ``env['XLA_FLAGS']``.

    Returns the flags actually added (empty when opted out, not a TPU
    task, or every flag was already spelled by the operator).  Pass a
    dict for tests; defaults to ``os.environ`` — call before the
    first jax import in the process.
    """
    env = os.environ if env is None else env
    if env.get("TRAIN_XLA_OVERLAP", "1") in ("0", "false"):
        return []
    if not env.get("TPU_GENERATION"):
        return []
    if "cpu" in env.get("JAX_PLATFORMS", "").lower():
        return []
    current = env.get("XLA_FLAGS", "")
    # token-wise name match: a substring test would let the operator's
    # --..._fusion_fuse_all_gather spelling silently suppress the
    # shorter --..._fusion flag they never set
    current_names = {
        token.split("=", 1)[0] for token in current.split()
    }
    added = [
        flag for flag in OVERLAP_FLAGS
        if flag.split("=", 1)[0] not in current_names
    ]
    if added:
        env["XLA_FLAGS"] = " ".join(
            added + ([current] if current else [])
        )
    return added
