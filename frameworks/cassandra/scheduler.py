"""Cassandra-analogue scheduler customizations + entrypoint.

Reference: frameworks/cassandra/src/main/java/.../Main.java and its
two distinctive pieces —

* **SeedsResource** (api/SeedsResource.java, registered at
  Main.java:88): the ring's contact points as a service endpoint.
  Here GET /v1/seeds lists the first ``min(2, count)`` node instances
  (the reference's local-seed computation) with placement + liveness,
  merged with ``TASKCFG_ALL_REMOTE_SEEDS`` for multi-datacenter rings.
* **CassandraRecoveryPlanOverrider** (:38-67): a PERMANENT node
  replace must not be a bare relaunch — the replacement must know the
  address it is taking over (the ``-Dcassandra.replace_address``
  launch option).  Here the overrider phase relaunches the server
  with ``REPLACE_ADDRESS=<its own ring name>`` injected via the
  requirement's env overrides.

Run as a service process:

    python frameworks/cassandra/scheduler.py svc.yml --topology fleet.yml
"""

from __future__ import annotations

import os
import sys
from typing import List, Optional

sys.path.insert(
    0,
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
)

from dcos_commons_tpu.plan.phase import Phase
from dcos_commons_tpu.plan.step import (
    DeploymentStep,
    PodInstanceRequirement,
    RecoveryType,
)
from dcos_commons_tpu.plan.strategy import SerialStrategy
from dcos_commons_tpu.specification.specs import (
    ServiceSpec,
    task_full_name,
)

N_LOCAL_SEEDS = 2  # reference: Main.java local seed computation


def ring_name(spec: ServiceSpec, index: int) -> str:
    """The stable ring address of node ``index`` (the discovery name
    tasks advertise under — see /v1/endpoints "dns")."""
    return f"node-{index}.{spec.name}.{spec.service_tld}"


def make_node_replace_overrider(spec: ServiceSpec):
    """RecoveryPlanOverrider: PERMANENT node replaces carry the
    replace_address; everything else keeps default recovery."""

    def overrider(
        pod_type: str, instances: List[int], recovery_type: RecoveryType
    ) -> Optional[Phase]:
        if pod_type != "node" or recovery_type is not RecoveryType.PERMANENT:
            return None
        pod = spec.pod("node")
        steps = [
            DeploymentStep(
                f"replace-node-{index}",
                PodInstanceRequirement(
                    pod=pod, instances=[index],
                    tasks_to_launch=["server"],
                    recovery_type=RecoveryType.PERMANENT,
                    # the replacement takes over its predecessor's ring
                    # position (reference: replace_address appended to
                    # the launch command)
                    env_overrides={
                        "REPLACE_ADDRESS": ring_name(spec, index),
                    },
                ),
            )
            for index in instances
        ]
        return Phase(
            f"replace-node-{'-'.join(map(str, instances))}",
            steps,
            SerialStrategy(),
        )

    return overrider


def make_seeds_routes(scheduler):
    """GET /v1/seeds — the SeedsResource analogue: local seeds (first
    min(2, count) instances) with host + liveness, plus any configured
    remote seeds (TASKCFG_ALL_REMOTE_SEEDS, the multi-DC contract)."""

    def seeds(_match, _query):
        spec = scheduler.spec
        statuses = scheduler.state_store.fetch_statuses()
        count = spec.pod("node").count
        local = []
        for index in range(min(N_LOCAL_SEEDS, count)):
            full = task_full_name("node", index, "server")
            info = scheduler.state_store.fetch_task(full)
            status = statuses.get(full)
            local.append({
                "seed": ring_name(spec, index),
                "host": info.agent_id if info else None,
                "state": status.state.value if status else None,
            })
        remote = [
            s for s in os.environ.get(
                "TASKCFG_ALL_REMOTE_SEEDS", ""
            ).split(",") if s
        ]
        return 200, {"seeds": local, "remote_seeds": remote}

    return [("GET", r"/v1/seeds", seeds)]


def main(argv: Optional[List[str]] = None) -> int:
    from dcos_commons_tpu.runtime.runner import serve_main

    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0].startswith("-"):
        argv.insert(0, os.path.join(os.path.dirname(__file__), "svc.yml"))
    return serve_main(
        argv,
        builder_hook=lambda builder, spec: builder.add_recovery_overrider(
            make_node_replace_overrider(spec)
        ),
        routes_hook=make_seeds_routes,
    )


if __name__ == "__main__":
    raise SystemExit(main())
